# ruleset-analysis-tpu — developer targets.
#
# NOTE (tier-1 calibration, tests/conftest.py): NEVER run these targets
# concurrently with the tier-1 gate run on the 1-core container — a
# parallel python process starves the distributed rendezvous tests and
# fabricates failures.  Run `make lint`, THEN the gate.

.PHONY: lint lint-fast test chaos obs postmortem servescale epochstore

# Static program-invariant lint (DESIGN §18): abstract-eval traces of
# the full shipping step grid + the repo registry audit.  No device, no
# XLA compile — finishes in well under 60 s on one CPU core.
lint:
	JAX_PLATFORMS=cpu python tools/ralint.py

# The tier-1 representative subset (what tests/test_ralint.py runs).
lint-fast:
	JAX_PLATFORMS=cpu python tools/ralint.py --fast

# The tier-1 suite (see ROADMAP.md for the exact gate invocation).
test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# Seeded chaos subset (DESIGN §9/§19): the tier-1 fault schedules plus
# the transient retry-recovery schedules and the WAL/degraded-mode
# suites.  Exit-coded for CI; same 1-core caveat as the gate above.
chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py tests/test_retry.py \
		tests/test_wal.py tests/test_failover.py -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# Observability-plane subset (DESIGN §15/§20/§24): the obs timeline +
# flight-recorder + device-attribution suites plus the window-lineage /
# SLO burn-rate suite (lineage record identity under failover replay,
# ledger chaos, doctor join, burn-rate hysteresis, /metrics parity).
# Exit-coded for CI; same 1-core caveat as the gate above.
obs:
	JAX_PLATFORMS=cpu python -m pytest tests/test_obs.py tests/test_flightrec.py \
		tests/test_devprof.py tests/test_lineage.py -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# Multi-host serve scaling acceptance (DESIGN §22): 1-host vs 2-host
# loopback soak over the same corpus — merged distributed windows must
# be bit-identical to the single-host replay of the union, with zero
# silent drops and a whole-host-kill chaos leg.  Writes the
# SERVESCALE_r19_cpu.json evidence artifact shape.  Same 1-core caveat:
# never run concurrently with the tier-1 gate.
servescale:
	JAX_PLATFORMS=cpu python bench_suite.py servescale

# Durable epoch-store acceptance (DESIGN §25): segment-tree range
# queries >= 10x a naive linear fold and bit-identical to it, the
# spill-armed serve within 2% of disarmed, and a mid-compaction crash
# leaving a readable store with zero lost epochs.  Writes the
# EPOCHSTORE_r22_cpu.json evidence artifact shape.  Same 1-core caveat:
# never run concurrently with the tier-1 gate.
epochstore:
	JAX_PLATFORMS=cpu python bench_suite.py epochstore

# Doctor acceptance path (DESIGN §20): chaos-killed runs must leave a
# complete postmortem bundle the doctor can diagnose (failing stage +
# fired site), clean exits must leave none, and the serve /metrics
# latency histograms must agree between JSON and prom.  Exit-coded.
postmortem:
	JAX_PLATFORMS=cpu python -m pytest tests/test_flightrec.py -q \
		-m 'not slow' --continue-on-collection-errors -p no:cacheprovider
