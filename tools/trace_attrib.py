#!/usr/bin/env python
"""Attribute device-step time from a jax.profiler Chrome trace.

Usage:
    python tools/trace_attrib.py [trace.json[.gz] ...]

Defaults to every ``*.trace.json.gz`` under ``profiles/``.  For each
process track, prints total duration by **semantic stage** where the
events carry ``jax.named_scope`` labels (the ``ra.*`` taxonomy every
register-update stage traces under since PR 8 — DESIGN §14), falling
back to the raw event name where they don't (pre-scope captures, host
runtime events).  The classifier is IMPORTED from
``ruleset_analysis_tpu.runtime.devprof`` — the same function the
in-process capture windows use — so offline and in-process attribution
can never disagree about what stage an op belongs to.

This is the offline half of the attribution plane: good for committed
TPU captures taken through ``--profile-dir`` or TensorBoard.  For
repeatable in-process capture (bounded window, optimized-HLO mapping
for backends whose event names are bare instruction names, per-stage
static FLOPs/bytes, diffable summaries) use ``run --devprof-out`` and
``tools/trace_diff.py`` instead.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ruleset_analysis_tpu.runtime.devprof import classify_event_name  # noqa: E402
from ruleset_analysis_tpu.stages import STAGES  # noqa: E402  (the ONE taxonomy)


def load_events(path: str) -> list[dict]:
    """Chrome trace events from ``.json`` or ``.json.gz`` (either form)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        return data.get("traceEvents", [])
    return data  # bare event-array form is also valid Chrome JSON


def attribute(path: str, top: int = 20) -> dict:
    """Per-(process, label) totals; label = ra.* stage or raw event name."""
    ev = load_events(path)
    names = {
        e["pid"]: e["args"].get("name", "")
        for e in ev
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and isinstance(e.get("args"), dict)
    }
    tot: dict = collections.defaultdict(float)
    cnt: collections.Counter = collections.Counter()
    scoped_us = 0.0
    total_us = 0.0
    unregistered: set = set()
    for e in ev:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        stage = classify_event_name(e.get("name", ""), e.get("args"))
        label = stage if stage is not None else e.get("name", "?")[:90]
        if stage is not None and stage not in STAGES:
            # syntactically an ra.* scope, but absent from the registered
            # taxonomy (stages.py) — someone added a scope without
            # registering it; the static linter flags the same drift
            unregistered.add(stage)
        key = (names.get(e["pid"], str(e["pid"])), label)
        tot[key] += e["dur"]
        cnt[key] += 1
        total_us += e["dur"]
        if stage is not None:
            scoped_us += e["dur"]
    return {
        "path": path,
        "events": len(ev),
        "total_us": total_us,
        "scoped_us": scoped_us,
        "unregistered_stages": sorted(unregistered),
        "rows": [
            {"process": proc, "label": name, "us": d, "count": cnt[(proc, name)]}
            for (proc, name), d in sorted(tot.items(), key=lambda kv: -kv[1])[:top]
        ],
    }


def render(a: dict) -> str:
    out = [f"== {a['path']} ({a['events']} events) =="]
    if a["total_us"]:
        out.append(
            f"  {100.0 * a['scoped_us'] / a['total_us']:.1f}% of span time "
            "carries a named ra.* stage label"
            if a["scoped_us"]
            else "  no named-scope labels found (pre-scope capture or CPU "
            "thunk names); showing raw event names — use `run "
            "--devprof-out` for semantic attribution on this backend"
        )
    if a.get("unregistered_stages"):
        out.append(
            "  WARNING: ra.* scopes not in the registered taxonomy "
            f"(stages.py): {', '.join(a['unregistered_stages'])}"
        )
    for r in a["rows"]:
        out.append(
            f"{r['us'] / 1e3:10.1f} ms  x{r['count']:>6}  "
            f"[{r['process']}] {r['label']}"
        )
    return "\n".join(out)


def main(argv: list[str]) -> int:
    paths = argv or sorted(
        glob.glob("profiles/**/*.trace.json.gz", recursive=True)
        + glob.glob("profiles/**/*.trace.json", recursive=True)
    )
    if not paths:
        print("no traces found under profiles/", file=sys.stderr)
        return 1
    rc = 0
    for p in paths:
        try:
            print(render(attribute(p)))
            print()
        except (OSError, ValueError) as e:
            print(f"error: unreadable trace {p!r}: {e}", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
