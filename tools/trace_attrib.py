#!/usr/bin/env python
"""Attribute device-step time from a committed jax.profiler Chrome trace.

Usage:
    python tools/trace_attrib.py [trace.json.gz ...]

Defaults to every ``vm.trace.json.gz`` under ``profiles/``.  Prints total
duration by event name per process track (TPU device vs host), which is
how the DESIGN.md §6b claim was derived: the fused analysis step splits
across ~7 comparable device fusions — the batch-sized register scatters —
so the TPU step is scatter-bound, not match-bound.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import sys


def attribute(path: str, top: int = 20) -> None:
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    ev = data.get("traceEvents", [])
    names = {
        e["pid"]: e["args"].get("name", "")
        for e in ev
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    tot: dict = collections.defaultdict(float)
    cnt: collections.Counter = collections.Counter()
    for e in ev:
        if e.get("ph") == "X" and "dur" in e:
            key = (names.get(e["pid"], str(e["pid"])), e["name"][:90])
            tot[key] += e["dur"]
            cnt[key] += 1
    print(f"== {path} ({len(ev)} events) ==")
    for (proc, name), d in sorted(tot.items(), key=lambda kv: -kv[1])[:top]:
        print(f"{d / 1e3:10.1f} ms  x{cnt[(proc, name)]:>5}  [{proc}] {name}")
    print()


def main(argv: list[str]) -> int:
    paths = argv or sorted(glob.glob("profiles/**/*.trace.json.gz", recursive=True))
    if not paths:
        print("no traces found under profiles/", file=sys.stderr)
        return 1
    for p in paths:
        attribute(p)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
