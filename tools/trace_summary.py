#!/usr/bin/env python
"""Per-stage occupancy + top stalls from a merged pipeline trace.

Usage:
    python tools/trace_summary.py TRACE.json [--json] [--top N]

Reads the merged Chrome trace the observability plane writes
(``run --trace-out DIR`` -> ``DIR/trace.json``; runtime/obs.py) and
answers the attribution question directly from the timeline:

- **occupancy** — for each span name (``ingest.produce``, ``ingest.pack``,
  ``step.dispatch``, ``feeder.parse``, ``checkpoint.save``, ...), total
  busy time as a percentage of the trace wall window, with event counts
  and mean durations.  Parallel tracks (producer thread, feeder worker
  processes) each contribute their own busy time, so totals over 100%
  mean real overlap — exactly what the pipelined ingest engine exists
  to produce.
- **top stalls** — the longest ``ingest.starved`` (parse-bound) and
  ``ingest.backpressure`` (device-bound) intervals, with their offsets
  into the run, so "where did the pipeline wait" has a concrete answer.
- **instants** — fault-site firings, checkpoint commits, elastic
  detections, counted by name.
- **serve** — for traces from the always-on ``serve`` mode: window
  rotation count + latency (``serve.rotate``), reload pauses
  (``serve.reload``), and ``listener.drop`` instants.
- **feed** — for runs on the per-chip ring feeder (``--feed-mode
  ring``): per-ring occupancy %, producer-partition imbalance, and
  starved-chip seconds, from the ``feeder.summary`` instant the ring
  coordinator emits at teardown.
- **devprof** — when a device attribution capture ran in-process
  (``run/serve --devprof-out``, runtime/devprof.py): per-stage device
  occupancy %, the top stage by time, and the unattributed fraction,
  read from the ``devprof.summary`` instant the capture emits onto the
  obs timeline (the full table lives in the capture's devprof.json).
- **lineage** — when a ``lineage.jsonl`` window-provenance ledger sits
  beside the trace (serve runs with ``--lineage on``, DESIGN §24): the
  record/kind counts, the last fully-published window, the first
  missing/incomplete one, and any contiguity gaps.
- **retries** — the transient-fault survival plane (DESIGN §19):
  per-site retry attempts with their summed backoff, recoveries, and
  giveups, from the ``retry.attempt``/``retry.recovered``/
  ``retry.giveup`` instants the policy engine emits.

``bench_suite.py obs`` imports :func:`summarize` to record stage
attribution in its artifact; tests assert the merged traces of chaos
runs stay summarizable.
"""

from __future__ import annotations

import argparse
import collections
import gzip
import json
import os
import sys

#: span names whose duration IS waiting, reported as stalls not work
STALL_NAMES = ("ingest.starved", "ingest.backpressure")


def _load_events(path: str) -> tuple[list[dict], dict | None]:
    """Events + the postmortem bundle when ``path`` is one.

    A flight-recorder ``postmortem.json`` (runtime/flightrec.py, DESIGN
    §20) holds per-PID ring shards of Chrome-trace-shaped events, so the
    SAME occupancy/stall/instant machinery below reads a crash bundle —
    the ``blackbox`` block carries the bundle-only facts (dump trigger,
    cursors, failing stage).
    """
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict) and data.get("kind") == "ra-postmortem":
        events = [
            e
            for shard in data.get("shards", [])
            for e in shard.get("ring_events", [])
        ]
        return events, data
    if isinstance(data, dict):
        return data.get("traceEvents", []), None
    return data, None  # bare event-array form is also valid Chrome JSON


def _blackbox_block(bundle: dict) -> dict:
    """The postmortem-only facts: trigger, cursors, final-window view."""
    a = bundle.get("analysis", {})
    return {
        "trigger": bundle.get("trigger"),
        "exit_code": bundle.get("exit_code"),
        "error": bundle.get("error"),
        "error_type": bundle.get("error_type"),
        "failing_stage": a.get("failing_stage"),
        "fault_sites_fired": a.get("fault_sites_fired") or {},
        "shards": [
            {
                "role": s.get("role"),
                "pid": s.get("pid"),
                "trigger": s.get("trigger"),
                "ring_events": len(s.get("ring_events", [])),
                "ring_total": s.get("ring_total"),
                # the final ring window's per-stage busy % — what the
                # process was doing in its last recorded seconds
                "stage_occupancy_pct": next(
                    (
                        p.get("stage_occupancy_pct")
                        for p in a.get("per_shard", [])
                        if p.get("pid") == s.get("pid")
                    ),
                    {},
                ),
                "cursors": s.get("cursors", {}),
            }
            for s in bundle.get("shards", [])
        ],
        "queue_depths": a.get("queue_depths") or {},
        "retries": a.get("retries") or {},
        "degraded": a.get("degraded") or [],
    }


def _lineage_block(path: str) -> dict | None:
    """Window-provenance summary from a lineage.jsonl beside the trace.

    Serve runs with ``--lineage on`` (the default) append one sealed
    record per published window to ``serve_dir/lineage.jsonl``; traces
    and postmortem bundles usually land in (or under) that same dir.
    Stdlib-only twin of runtime/report.py::lineage_frontier so this
    tool stays runnable on a box with nothing installed.
    """
    d = os.path.dirname(os.path.abspath(path))
    lpath = None
    for cand in (d, os.path.dirname(d)):
        c = os.path.join(cand, "lineage.jsonl")
        if os.path.isfile(c):
            lpath = c
            break
    if lpath is None:
        return None
    by_id: dict[int, dict] = {}
    kinds: collections.Counter = collections.Counter()
    paths: collections.Counter = collections.Counter()
    try:
        with open(lpath, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    r = json.loads(line)
                except ValueError:
                    continue  # torn final line after a crash is legal
                kinds[str(r.get("kind"))] += 1
                paths[str(r.get("path"))] += 1
                if r.get("kind") != "merged" and r.get("window") is not None:
                    by_id[int(r["window"])] = r  # last write wins
    except OSError:
        return None
    ids = sorted(by_id)
    gaps = (
        [w for w in range(ids[0], ids[-1] + 1) if w not in by_id]
        if ids else []
    )
    last_complete = None
    first_incomplete = gaps[0] if gaps else None
    for wid in ids:
        if by_id[wid].get("incomplete"):
            if first_incomplete is None or wid < first_incomplete:
                first_incomplete = wid
        else:
            last_complete = wid
    return {
        "path": lpath,
        "records": sum(kinds.values()),
        "kinds": dict(kinds),
        "paths": dict(paths),
        "last_complete": last_complete,
        "first_incomplete": first_incomplete,
        "gaps": gaps[:8],
    }


def summarize(path: str, top: int = 5) -> dict:
    """Machine-readable attribution for one merged trace file."""
    events, bundle = _load_events(path)
    spans = [e for e in events if e.get("ph") == "X" and "ts" in e]
    instants = collections.Counter(
        e.get("name", "?") for e in events if e.get("ph") == "i"
    )
    pids = {e.get("pid") for e in events if "pid" in e}
    tracks = {(e.get("pid"), e.get("tid")) for e in spans}
    if not spans:
        return {
            "path": path,
            "events": len(events),
            "processes": len(pids),
            "tracks": 0,
            "wall_sec": 0.0,
            "stages": {},
            "top_stalls": [],
            "instants": dict(instants),
            **({"blackbox": _blackbox_block(bundle)} if bundle else {}),
            **({"lineage": lb} if (lb := _lineage_block(path)) else {}),
        }
    t_min = min(e["ts"] for e in spans)
    t_max = max(e["ts"] + e.get("dur", 0) for e in spans)
    wall_us = max(1, t_max - t_min)
    by_stage: dict[str, dict] = {}
    for e in spans:
        s = by_stage.setdefault(e["name"], {"busy_us": 0, "count": 0})
        s["busy_us"] += e.get("dur", 0)
        s["count"] += 1
    stages = {
        name: {
            "occupancy_pct": round(100.0 * s["busy_us"] / wall_us, 2),
            "busy_sec": round(s["busy_us"] / 1e6, 4),
            "count": s["count"],
            "mean_ms": round(s["busy_us"] / s["count"] / 1e3, 3),
        }
        for name, s in sorted(
            by_stage.items(), key=lambda kv: -kv[1]["busy_us"]
        )
    }
    stalls = sorted(
        (e for e in spans if e["name"] in STALL_NAMES),
        key=lambda e: -e.get("dur", 0),
    )[:top]
    # flow-coalescing attribution: each ingest.coalesce span carries the
    # batch's raw/unique row counts in its args, so the trace alone
    # answers "what compaction ratio did this run actually see"
    coalesce = None
    raw = unique = 0
    for e in spans:
        if e["name"] == "ingest.coalesce":
            a = e.get("args") or {}
            raw += int(a.get("raw", 0))
            unique += int(a.get("unique", 0))
    if raw or unique:
        coalesce = {
            "raw_rows": raw,
            "unique_rows": unique,
            "compaction_ratio": round(raw / max(unique, 1), 4),
        }
    # serve-mode attribution: rotation latency (serve.rotate covers the
    # flush + ring push + publish of one window) and the reload pause
    # (serve.reload = how long live analysis stood still for the swap);
    # listener.drop instants are the trace's copy of the drop counter
    serve = None
    rotations = [e for e in spans if e["name"] == "serve.rotate"]
    reloads = [e for e in spans if e["name"] == "serve.reload"]
    if rotations or reloads:
        durs = [e.get("dur", 0) for e in rotations]
        serve = {
            "rotations": len(rotations),
            **(
                {
                    "rotation_mean_ms": round(sum(durs) / len(durs) / 1e3, 3),
                    "rotation_max_ms": round(max(durs) / 1e3, 3),
                }
                if durs
                else {}
            ),
            "reloads": len(reloads),
            **(
                {
                    "reload_pause_ms": [
                        round(e.get("dur", 0) / 1e3, 3) for e in reloads
                    ]
                }
                if reloads
                else {}
            ),
            "listener_drops": instants.get("listener.drop", 0),
        }
    # autoscale attribution: every policy decision is an instant with
    # its full evidence attached, every serve-side actuation a span —
    # so "what did the autoscaler do, on what grounds, and how fast did
    # it take effect" is answerable from the trace alone
    autoscale = None
    decides = sorted(
        (e for e in events
         if e.get("ph") == "i" and e.get("name") == "autoscale.decide"
         and "ts" in e),
        key=lambda e: e["ts"],
    )
    applies = [e for e in spans if e["name"] == "autoscale.apply"]
    if decides or applies:
        flaps = 0
        prev = None
        for e in decides:
            a = e.get("args") or {}
            if prev is not None:
                pa = prev.get("args") or {}
                window_us = float(a.get("damping_window_sec", 0)) * 1e6
                if (
                    a.get("direction") != pa.get("direction")
                    and e["ts"] - prev["ts"] < window_us
                ):
                    flaps += 1
            prev = e
        durs = [e.get("dur", 0) for e in applies]
        autoscale = {
            "decisions": [
                {
                    "at_sec": round((e["ts"] - t_min) / 1e6, 3),
                    **{
                        k: (e.get("args") or {}).get(k)
                        for k in ("seq", "direction", "from_world",
                                  "to_world", "reason", "actuate")
                    },
                    "evidence": (e.get("args") or {}).get("evidence"),
                }
                for e in decides
            ],
            "scale_out": sum(
                1 for e in decides
                if (e.get("args") or {}).get("direction") == "out"
            ),
            "scale_in": sum(
                1 for e in decides
                if (e.get("args") or {}).get("direction") == "in"
            ),
            "flaps": flaps,
            **(
                {
                    # serve-side: the apply span IS the time-to-effect
                    "applies": len(applies),
                    "time_to_effect_mean_ms": round(
                        sum(durs) / len(durs) / 1e3, 3
                    ),
                    "time_to_effect_max_ms": round(max(durs) / 1e3, 3),
                }
                if durs
                else {}
            ),
            # elastic-side actuation markers (planned retirements and
            # parked standbys; time-to-effect lands in the report's
            # totals.autoscale.applied records)
            "retirements": instants.get("autoscale.retire", 0),
            "standby_parks": instants.get("autoscale.standby", 0),
        }
    # feed-fleet attribution (ISSUE 11): the ring feeder pushes one
    # feeder.summary instant at teardown — per-ring occupancy %, the
    # producer-partition imbalance, and how long each chip's ring sat
    # dry while the coordinator waited on it (starved-chip seconds)
    feed = None
    feed_instants = [
        e for e in events
        if e.get("ph") == "i" and e.get("name") == "feeder.summary"
        and isinstance(e.get("args"), dict)
    ]
    if feed_instants:
        a = feed_instants[-1]["args"]  # latest feed run wins
        feed = {
            "mode": a.get("mode"),
            "rings": a.get("rings"),
            "ring_depth": a.get("ring_depth"),
            "workers": a.get("workers"),
            "groups": a.get("groups"),
            "ring_occupancy_pct": a.get("ring_occupancy_pct"),
            "partition_imbalance_pct": a.get("partition_imbalance_pct"),
            "starved_sec": a.get("starved_sec"),
            "starved_total_sec": a.get("starved_total_sec"),
        }
    # device attribution capture (run/serve --devprof-out): the capture
    # pushes one devprof.summary instant whose args are the flat gauges
    # — per-stage device occupancy, top stage, attributed fraction
    devprof = None
    dp_instants = [
        e for e in events
        if e.get("ph") == "i" and e.get("name") == "devprof.summary"
        and isinstance(e.get("args"), dict)
    ]
    if dp_instants:
        a = dp_instants[-1]["args"]  # latest capture wins
        stage_pcts = {
            k[len("devprof_pct_"):].replace("_", ".", 1): v
            for k, v in a.items()
            if k.startswith("devprof_pct_")
        }
        devprof = {
            "steps_profiled": a.get("devprof_steps_profiled"),
            "attributed_frac": a.get("devprof_attributed_frac"),
            "top_stage": a.get("devprof_top_stage"),
            "top_stage_pct": a.get("devprof_top_stage_pct"),
            "stage_pct": dict(
                sorted(stage_pcts.items(), key=lambda kv: -(kv[1] or 0))
            ),
            "unattributed_pct": (
                round(100.0 * (1.0 - a["devprof_attributed_frac"]), 2)
                if isinstance(a.get("devprof_attributed_frac"), (int, float))
                else None
            ),
        }
    # retry attribution (DESIGN §19): every retry decision is an instant
    # with its site/attempt/delay, recoveries and giveups likewise — so
    # "what did the survival plane absorb, and what escalated" is
    # answerable from the trace alone
    retries = None
    retry_by_site: dict[str, dict] = {}
    for e in events:
        if e.get("ph") != "i" or not isinstance(e.get("args"), dict):
            continue
        name = e.get("name", "")
        if name not in ("retry.attempt", "retry.recovered", "retry.giveup"):
            continue
        site = e["args"].get("site", "?")
        s = retry_by_site.setdefault(
            site, {"attempts": 0, "recoveries": 0, "giveups": 0,
                   "backoff_sec": 0.0}
        )
        if name == "retry.attempt":
            s["attempts"] += 1
            s["backoff_sec"] = round(
                s["backoff_sec"] + float(e["args"].get("delay_sec", 0.0)), 4
            )
        elif name == "retry.recovered":
            s["recoveries"] += 1
        else:
            s["giveups"] += 1
    if retry_by_site:
        retries = {
            "sites": dict(sorted(retry_by_site.items())),
            "attempts": sum(s["attempts"] for s in retry_by_site.values()),
            "recoveries": sum(s["recoveries"] for s in retry_by_site.values()),
            "giveups": sum(s["giveups"] for s in retry_by_site.values()),
        }

    # failover attribution (DESIGN §23): lease.acquired carries the won
    # term + election wait, distserve.failover.replay the spool-replay
    # accounting, lease.fenced every fencing event — time-to-takeover
    # (election wait + replay) and "who fenced whom" read off the trace
    failover = None
    acquired = [
        e for e in events
        if e.get("ph") == "i" and e.get("name") == "lease.acquired"
        and isinstance(e.get("args"), dict)
    ]
    fenced_ev = [
        e for e in events
        if e.get("ph") == "i" and e.get("name") == "lease.fenced"
        and isinstance(e.get("args"), dict)
    ]
    replays = [
        e for e in events
        if e.get("ph") == "i" and e.get("name") == "distserve.failover.replay"
        and isinstance(e.get("args"), dict)
    ]
    if acquired or fenced_ev or replays:
        wait_sec = sum(
            float(e["args"].get("wait_sec", 0.0)) for e in acquired
        )
        replay_sec = sum(
            float(e["args"].get("takeover_sec", 0.0)) for e in replays
        )
        failover = {
            "terms_won": [int(e["args"].get("term", 0)) for e in acquired],
            "time_to_takeover_sec": round(wait_sec + replay_sec, 3),
            "election_wait_sec": round(wait_sec, 3),
            "epochs_replayed": sum(
                int(e["args"].get("epochs", 0)) for e in replays
            ),
            "windows_replayed": sum(
                int(e["args"].get("windows", 0)) for e in replays
            ),
            "replay_refused": instants.get("distserve.replay.refused", 0),
            "fencing_events": [
                {
                    "fenced_term": int(e["args"].get("term", 0)),
                    "winner_term": int(e["args"].get("winner_term", 0)),
                    "winner": e["args"].get("winner", "?"),
                }
                for e in fenced_ev
            ],
            "partitions": instants.get("serve.host.partition", 0),
            "partition_heals": instants.get("serve.host.partition_heal", 0),
        }
    return {
        "path": path,
        "events": len(events),
        "processes": len(pids),
        "tracks": len(tracks),
        "wall_sec": round(wall_us / 1e6, 4),
        "stages": stages,
        "top_stalls": [
            {
                "kind": e["name"],
                "at_sec": round((e["ts"] - t_min) / 1e6, 4),
                "dur_ms": round(e.get("dur", 0) / 1e3, 3),
                "pid": e.get("pid"),
            }
            for e in stalls
        ],
        "instants": dict(instants),
        **({"coalesce": coalesce} if coalesce else {}),
        **({"serve": serve} if serve else {}),
        **({"autoscale": autoscale} if autoscale else {}),
        **({"feed": feed} if feed else {}),
        **({"devprof": devprof} if devprof else {}),
        **({"retries": retries} if retries else {}),
        **({"failover": failover} if failover else {}),
        **({"blackbox": _blackbox_block(bundle)} if bundle else {}),
        **({"lineage": lb} if (lb := _lineage_block(path)) else {}),
    }


def render(s: dict) -> str:
    out = [
        f"== {s['path']} ==",
        f"  {s['events']} events, {s['processes']} process(es), "
        f"{s['tracks']} span track(s), wall {s['wall_sec']:.3f}s",
        "  stage occupancy (busy / wall; >100% total = overlap):",
    ]
    for name, st in s["stages"].items():
        out.append(
            f"    {st['occupancy_pct']:6.2f}%  {st['busy_sec']:9.3f}s  "
            f"x{st['count']:<6} mean {st['mean_ms']:8.3f} ms  {name}"
        )
    if s["top_stalls"]:
        out.append("  top stall intervals:")
        for st in s["top_stalls"]:
            out.append(
                f"    +{st['at_sec']:9.3f}s  {st['dur_ms']:9.3f} ms  "
                f"[pid {st['pid']}] {st['kind']}"
            )
    if s.get("coalesce"):
        c = s["coalesce"]
        out.append(
            f"  coalesce: {c['raw_rows']} raw -> {c['unique_rows']} unique "
            f"rows ({c['compaction_ratio']:.2f}x compaction)"
        )
    if s.get("serve"):
        sv = s["serve"]
        line = f"  serve: {sv['rotations']} rotation(s)"
        if "rotation_mean_ms" in sv:
            line += (
                f" (mean {sv['rotation_mean_ms']:.1f} ms, "
                f"max {sv['rotation_max_ms']:.1f} ms)"
            )
        line += f", {sv['reloads']} reload(s)"
        if sv.get("reload_pause_ms"):
            line += f" (pause {', '.join(f'{p:.1f}' for p in sv['reload_pause_ms'])} ms)"
        line += f", {sv['listener_drops']} listener drop(s)"
        out.append(line)
    if s.get("autoscale"):
        a = s["autoscale"]
        line = (
            f"  autoscale: {a['scale_out']} out / {a['scale_in']} in, "
            f"{a['flaps']} flap(s)"
        )
        if "time_to_effect_mean_ms" in a:
            line += (
                f", time-to-effect mean {a['time_to_effect_mean_ms']:.1f} ms"
                f" max {a['time_to_effect_max_ms']:.1f} ms"
            )
        if a.get("retirements"):
            line += f", {a['retirements']} planned retirement(s)"
        out.append(line)
        for d in a["decisions"]:
            ev = d.get("evidence") or {}
            grounds = ""
            sig = ev.get("pressure" if d.get("reason") == "backpressure"
                         else "starvation")
            if isinstance(sig, dict):
                grounds = (
                    f"  [min {sig.get('min')} >= thr {sig.get('threshold')}"
                    f" over {ev.get('window_sec')}s]"
                )
            out.append(
                f"    +{d['at_sec']:9.3f}s  #{d.get('seq')} "
                f"{d.get('direction')} {d.get('from_world')}->"
                f"{d.get('to_world')} ({d.get('reason')}){grounds}"
            )
    if s.get("feed"):
        fd = s["feed"]
        out.append(
            f"  feed: {fd.get('mode')} x{fd.get('rings')} ring(s) depth "
            f"{fd.get('ring_depth')}, {fd.get('workers')} worker(s), "
            f"{fd.get('groups')} group(s); partition imbalance "
            f"{fd.get('partition_imbalance_pct')}%, starved "
            f"{fd.get('starved_total_sec')}s total"
        )
        occ = fd.get("ring_occupancy_pct") or []
        sts = fd.get("starved_sec") or []
        for j, pct in enumerate(occ):
            starved = sts[j] if j < len(sts) else 0.0
            out.append(
                f"    ring {j}: occupancy {pct:6.2f}%  starved {starved:.3f}s"
            )
    if s.get("devprof"):
        dp = s["devprof"]
        af = dp.get("attributed_frac")
        line = f"  devprof: {dp.get('steps_profiled')} step(s) captured"
        if af is not None:
            line += (
                f", {100 * af:.1f}% attributed "
                f"({dp.get('unattributed_pct')}% unattributed)"
            )
        if dp.get("top_stage"):
            line += f", top stage {dp['top_stage']} ({dp.get('top_stage_pct')}%)"
        out.append(line)
        for name, pct in dp.get("stage_pct", {}).items():
            out.append(f"    {pct:6.2f}%  {name}")
    if s.get("retries"):
        r = s["retries"]
        out.append(
            f"  retries: {r['attempts']} attempt(s), {r['recoveries']} "
            f"recovery(ies), {r['giveups']} giveup(s)"
        )
        for site, st in r["sites"].items():
            out.append(
                f"    {site}: {st['attempts']} retry(ies) "
                f"({st['backoff_sec']:.3f}s backoff), "
                f"{st['recoveries']} recovered, {st['giveups']} gave up"
            )
    if s.get("failover"):
        fo = s["failover"]
        terms = ", ".join(str(t) for t in fo["terms_won"]) or "-"
        out.append(
            f"  failover: term(s) {terms} won in "
            f"{fo['time_to_takeover_sec']:.3f}s "
            f"({fo['election_wait_sec']:.3f}s election), "
            f"{fo['epochs_replayed']} epoch(s) -> "
            f"{fo['windows_replayed']} window(s) replayed, "
            f"{fo['replay_refused']} refused"
        )
        for fe in fo["fencing_events"]:
            out.append(
                f"    fenced: term {fe['fenced_term']} lost to term "
                f"{fe['winner_term']} ({fe['winner']})"
            )
        if fo["partitions"] or fo["partition_heals"]:
            out.append(
                f"    partitions: {fo['partitions']} parked, "
                f"{fo['partition_heals']} healed"
            )
    if s.get("blackbox"):
        bb = s["blackbox"]
        out.append(
            f"  blackbox: trigger={bb['trigger']} exit_code={bb['exit_code']}"
            f" error={bb.get('error_type')}: {bb.get('error')}"
        )
        out.append(f"    failing stage: {bb.get('failing_stage')}")
        if bb.get("fault_sites_fired"):
            fired = ", ".join(
                f"{k} x{v}" for k, v in sorted(bb["fault_sites_fired"].items())
            )
            out.append(f"    fault sites fired: {fired}")
        for sh in bb.get("shards", []):
            out.append(
                f"    shard [{sh.get('role')} pid {sh.get('pid')}] "
                f"trigger={sh.get('trigger')} "
                f"({sh.get('ring_events')} of {sh.get('ring_total')} ring "
                f"events retained)"
            )
            occ = sh.get("stage_occupancy_pct") or {}
            for name, pct in list(occ.items())[:4]:
                out.append(f"      {pct:6.2f}%  {name}")
            if sh.get("cursors"):
                cur = ", ".join(
                    f"{k}={v}" for k, v in sorted(sh["cursors"].items())
                )
                out.append(f"      cursors: {cur}")
        if bb.get("degraded"):
            out.append(f"    degraded: {'; '.join(bb['degraded'])}")
    if s.get("lineage"):
        ln = s["lineage"]
        kinds = ", ".join(f"{k} x{v}" for k, v in sorted(ln["kinds"].items()))
        out.append(
            f"  lineage: {ln['records']} record(s) in {ln['path']} ({kinds})"
        )
        out.append(
            f"    last complete window: {ln['last_complete']}   "
            f"first missing/incomplete: {ln['first_incomplete']}"
        )
        if ln["gaps"]:
            out.append(f"    gap window id(s): {ln['gaps']}")
        off_live = {
            k: v for k, v in ln["paths"].items() if k not in ("live", "None")
        }
        if off_live:
            alt = ", ".join(f"{k} x{v}" for k, v in sorted(off_live.items()))
            out.append(f"    non-live publication paths: {alt}")
    if s["instants"]:
        marks = ", ".join(f"{k} x{v}" for k, v in sorted(s["instants"].items()))
        out.append(f"  instants: {marks}")
    return "\n".join(out)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="per-stage occupancy + top stalls from a merged "
        "--trace-out trace"
    )
    ap.add_argument("traces", nargs="+", help="merged trace.json file(s)")
    ap.add_argument("--json", action="store_true", help="machine output")
    ap.add_argument("--top", type=int, default=5, help="stall intervals to list")
    args = ap.parse_args(argv)
    rc = 0
    results = []
    for path in args.traces:
        try:
            results.append(summarize(path, top=args.top))
        except (OSError, ValueError) as e:
            print(f"error: unreadable trace {path!r}: {e}", file=sys.stderr)
            rc = 1
    if not results:
        return rc or 1
    if args.json:
        print(json.dumps(results if len(results) > 1 else results[0], indent=2))
    else:
        for s in results:
            print(render(s))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
