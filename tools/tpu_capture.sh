#!/bin/bash
# Resilient TPU-evidence capture (VERDICT r4 #1: make capture automatic).
#
# The axon tunnel comes and goes, and a process killed mid-TPU-operation
# can wedge it for everyone (see .claude/skills/verify gotchas).  So this
# loop never trusts a single long run:
#   1. probe the backend in a BOUNDED subprocess;
#   2. when it answers, run each outstanding suite config in its own
#      bounded subprocess, banking each result as it lands;
#   3. reassemble BENCH_SUITE_r04_tpu.json from everything banked so far
#      after every config — a later wedge can't lose earlier evidence;
#   4. sleep and repeat until every config is banked.
#
# Run detached:  setsid nohup tools/tpu_capture.sh > /tmp/tpu_capture.log 2>&1 &
# State lives in $BANK; artifacts land at the repo root (committed by the
# build session or, failing that, by the driver's end-of-round commit).
set -u
cd "$(dirname "$0")/.."
BANK=${BANK:-/tmp/tpu_bank_r04}
CONFIGS=(exact pallas multifw recall e2e stage)
PER_CONFIG_TIMEOUT=${PER_CONFIG_TIMEOUT:-2700}
PROBE_TIMEOUT=${PROBE_TIMEOUT:-90}
SLEEP_BETWEEN=${SLEEP_BETWEEN:-300}
#: Hard wall-clock deadline (seconds since launch): the loop must be gone
#: before the driver's own end-of-round bench needs the chip.
MAX_WALL=${MAX_WALL:-28800}
START_TS=$(date +%s)
mkdir -p "$BANK"

probe() {
    timeout "$PROBE_TIMEOUT" python - << 'EOF' > /dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
EOF
}

assemble() {
    local n_done=0 total=${#CONFIGS[@]}
    for c in "${CONFIGS[@]}"; do
        [ -s "$BANK/$c.jsonl" ] && n_done=$((n_done + 1))
    done
    local complete=false
    [ "$n_done" -eq "$total" ] && complete=true
    {
        echo "{\"note\": \"TPU run (axon tunnel), captured per-config by tools/tpu_capture.sh. cms/hll/topk accuracy lines carried from the round-4 fresh accuracy artifact (platform-independent).\", \"platform\": \"tpu\", \"suite_configs_completed\": $n_done, \"suite_configs_total\": $total, \"complete\": $complete}"
        for c in "${CONFIGS[@]}"; do
            [ -s "$BANK/$c.jsonl" ] && cat "$BANK/$c.jsonl"
        done
        grep -E '"config2_|"config3_|"config5_' BENCH_SUITE_r04_accuracy_cpu.json
    } > BENCH_SUITE_r04_tpu.json
    echo "assembled BENCH_SUITE_r04_tpu.json ($n_done/$total configs)" >&2
}

# an honest artifact exists from the start: 0/N configs, carried accuracy
# lines — replaced as configs bank
[ -s BENCH_SUITE_r04_tpu.json ] || assemble

while true; do
    if [ $(( $(date +%s) - START_TS )) -ge "$MAX_WALL" ]; then
        echo "$(date -u +%T) deadline (${MAX_WALL}s) reached; exiting" >&2
        assemble
        exit 0
    fi
    outstanding=()
    for c in "${CONFIGS[@]}"; do
        [ -s "$BANK/$c.jsonl" ] || outstanding+=("$c")
    done
    if [ ${#outstanding[@]} -eq 0 ]; then
        echo "$(date -u +%T) all configs banked; done" >&2
        assemble
        exit 0
    fi
    if probe; then
        echo "$(date -u +%T) probe ok; outstanding: ${outstanding[*]}" >&2
        # headline first: bench.py self-bounds and now includes the
        # rule-constant-specialized step + wire-ingest e2e leg; re-banking
        # it refreshes BENCH_r04_local.json with the faster kernel
        if [ ! -s "$BANK/headline.done" ]; then
            if python bench.py > "$BANK/headline.json" 2> "$BANK/headline.log" \
                    && grep -q '"platform": "tpu"' "$BANK/headline.json"; then
                cp "$BANK/headline.json" BENCH_r04_local.json
                touch "$BANK/headline.done"
                echo "$(date -u +%T) banked headline (tpu)" >&2
            else
                echo "$(date -u +%T) headline run not tpu-valid; will retry" >&2
            fi
        fi
        for c in "${outstanding[@]}"; do
            echo "$(date -u +%T) running config $c" >&2
            if timeout "$PER_CONFIG_TIMEOUT" python bench_suite.py "$c" \
                    > "$BANK/$c.tmp" 2> "$BANK/$c.log"; then
                if grep -q '^{' "$BANK/$c.tmp"; then
                    grep '^{' "$BANK/$c.tmp" > "$BANK/$c.jsonl"
                    echo "$(date -u +%T) banked $c" >&2
                    assemble
                else
                    echo "$(date -u +%T) $c produced no JSON line" >&2
                fi
            else
                echo "$(date -u +%T) $c failed/timed out (rc=$?); tunnel may be wedged" >&2
                break  # re-probe before burning time on the rest
            fi
        done
    else
        echo "$(date -u +%T) probe failed (tunnel down); sleeping ${SLEEP_BETWEEN}s" >&2
    fi
    sleep "$SLEEP_BETWEEN"
done
