#!/bin/bash
# Resilient TPU-evidence capture, round 5 (VERDICT r4 "Next round" #1).
#
# The axon tunnel comes and goes, and a process killed mid-TPU-operation
# can wedge it for everyone.  So this loop never trusts a single long run:
#   1. probe the backend in a BOUNDED subprocess;
#   2. when it answers, run each outstanding item in its own bounded
#      subprocess, banking each result as it lands;
#   3. reassemble BENCH_SUITE_r05_tpu.json from everything banked so far
#      after every item — a later wedge can't lose earlier evidence;
#   4. sleep and repeat until every item is banked or the deadline hits.
#
# Round-5 priority (VERDICT r4 #1): stage (validates the scatter
# attribution + counts formulations), pallas (first compiled run ever),
# headline re-capture (prices rule-constant specialization on TPU), e2e
# wire leg, multifw, recall, exact.  "headline" is bench.py itself and
# refreshes BENCH_r05_local.json.
#
# Run detached:  setsid nohup tools/tpu_capture.sh > /tmp/tpu_capture_r05.log 2>&1 &
# State lives in $BANK; artifacts land at the repo root (committed by the
# build session or, failing that, by the driver's end-of-round commit).
set -u
cd "$(dirname "$0")/.."
BANK=${BANK:-/tmp/tpu_bank_r05}
ITEMS=(stage pallas headline e2e multifw recall exact)
SUITE_TOTAL=6   # suite configs (headline is bench.py, counted separately)
PER_CONFIG_TIMEOUT=${PER_CONFIG_TIMEOUT:-2700}
PROBE_TIMEOUT=${PROBE_TIMEOUT:-90}
SLEEP_BETWEEN=${SLEEP_BETWEEN:-300}
#: Hard wall-clock deadline (seconds since launch): the loop must be gone
#: before the driver's own end-of-round bench needs the chip.
MAX_WALL=${MAX_WALL:-36000}
START_TS=$(date +%s)
mkdir -p "$BANK"

probe() {
    timeout "$PROBE_TIMEOUT" python - << 'EOF' > /dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
EOF
}

assemble() {
    local n_done=0
    for c in "${ITEMS[@]}"; do
        [ "$c" = headline ] && continue
        [ -s "$BANK/$c.jsonl" ] && n_done=$((n_done + 1))
    done
    local headline_done=false
    [ -s "$BANK/headline.done" ] && headline_done=true
    local complete=false
    [ "$n_done" -eq "$SUITE_TOTAL" ] && [ "$headline_done" = true ] && complete=true
    # Honest platform labeling (VERDICT r4 weak #1): the artifact claims
    # "tpu" only once at least one TPU-measured line exists in it.
    local platform='"pending_tpu_window"'
    { [ "$n_done" -gt 0 ] || [ "$headline_done" = true ]; } && platform='"tpu"'
    {
        echo "{\"note\": \"Round-5 TPU capture (axon tunnel), banked per-config by tools/tpu_capture.sh. cms/hll/topk accuracy lines carried from the FRESH round-5 accuracy artifact BENCH_SUITE_r05_accuracy_cpu.json (platform-independent).\", \"platform\": $platform, \"suite_configs_completed\": $n_done, \"suite_configs_total\": $SUITE_TOTAL, \"headline_recaptured\": $headline_done, \"complete\": $complete}"
        for c in "${ITEMS[@]}"; do
            [ "$c" = headline ] && continue
            [ -s "$BANK/$c.jsonl" ] && cat "$BANK/$c.jsonl"
        done
        grep -E '"config2_|"config3_|"config5_' BENCH_SUITE_r05_accuracy_cpu.json
    } > BENCH_SUITE_r05_tpu.json
    echo "assembled BENCH_SUITE_r05_tpu.json ($n_done/$SUITE_TOTAL configs, headline=$headline_done)" >&2
}

# an honest artifact exists from the start: 0/N configs, carried accuracy
# lines — replaced as items bank
[ -s BENCH_SUITE_r05_tpu.json ] || assemble

run_headline() {
    if timeout "$PER_CONFIG_TIMEOUT" python bench.py \
            > "$BANK/headline.json" 2> "$BANK/headline.log" \
            && grep -q '"platform": "tpu"' "$BANK/headline.json"; then
        cp "$BANK/headline.json" BENCH_r05_local.json
        echo done > "$BANK/headline.done"
        echo "$(date -u +%T) banked headline (tpu)" >&2
        return 0
    fi
    echo "$(date -u +%T) headline run not tpu-valid; will retry" >&2
    return 1
}

while true; do
    if [ $(( $(date +%s) - START_TS )) -ge "$MAX_WALL" ]; then
        echo "$(date -u +%T) deadline (${MAX_WALL}s) reached; exiting" >&2
        assemble
        exit 0
    fi
    outstanding=()
    for c in "${ITEMS[@]}"; do
        if [ "$c" = headline ]; then
            [ -s "$BANK/headline.done" ] || outstanding+=("$c")
        else
            [ -s "$BANK/$c.jsonl" ] || outstanding+=("$c")
        fi
    done
    if [ ${#outstanding[@]} -eq 0 ]; then
        echo "$(date -u +%T) all items banked; done" >&2
        assemble
        exit 0
    fi
    if probe; then
        echo "$(date -u +%T) probe ok; outstanding: ${outstanding[*]}" >&2
        for c in "${outstanding[@]}"; do
            echo "$(date -u +%T) running $c" >&2
            if [ "$c" = headline ]; then
                run_headline && assemble || break
                continue
            fi
            # recall runs at fleet shape on TPU (VERDICT r4 #6): 10k keys,
            # stacked layout, default 96-chunk (1e8-line) scale
            cfg_env=()
            [ "$c" = recall ] && cfg_env=(RA_RECALL_KEYS=10240 RA_RECALL_LAYOUT=stacked)
            if timeout "$PER_CONFIG_TIMEOUT" env "${cfg_env[@]}" python bench_suite.py "$c" \
                    > "$BANK/$c.tmp" 2> "$BANK/$c.log"; then
                if grep -q '^{' "$BANK/$c.tmp"; then
                    grep '^{' "$BANK/$c.tmp" > "$BANK/$c.jsonl"
                    echo "$(date -u +%T) banked $c" >&2
                    assemble
                else
                    echo "$(date -u +%T) $c produced no JSON line" >&2
                fi
            else
                echo "$(date -u +%T) $c failed/timed out (rc=$?); tunnel may be wedged" >&2
                break  # re-probe before burning time on the rest
            fi
        done
    else
        echo "$(date -u +%T) probe failed (tunnel down); sleeping ${SLEEP_BETWEEN}s" >&2
    fi
    sleep "$SLEEP_BETWEEN"
done
