#!/usr/bin/env python
"""ralint report mode — the static program-invariant lint, standalone.

Usage:
    python tools/ralint.py [--fast] [--json] [--skip-registry]

Traces every shipping step program (the full impl grid: counts_impl x
match_impl x update_impl x topk variants, v4+v6, flat+stacked) to a
closed jaxpr by abstract eval — no device data, no XLA compile — and
verifies the four invariant families of DESIGN §18:

  1. weight-linearity   taint walk from the weight plane to every
                        register sink (DESIGN §11); derived refusals
                        must equal config.WEIGHTED_INPUT_REFUSALS
  2. scatter safety     mode=drop everywhere; indices_are_sorted only
                        downstream of a lax.sort (§15)
  3. scope coverage     every register-update primitive attributes to
                        exactly one registered ra.* stage (§14)
  4. merge laws         every register output crosses its law's
                        collective (add64/add32 -> psum, max -> pmax,
                        candidates -> all_gather)

plus the repo registry audit (fault sites <-> call sites <-> tests;
CLI flags <-> README <-> PARITY; VOLATILE totals keys <-> producers).

Runs on CPU in seconds; exit 0 = every invariant proven (or typed-
refused), 1 = findings.  `make lint` wraps this.  NOTE (tier-1
calibration): never run this concurrently with the tier-1 gate on a
1-core container — a parallel python process starves the distributed
rendezvous tests and fabricates failures.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fast", action="store_true",
                    help="representative subset instead of the full grid")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--skip-registry", action="store_true")
    ap.add_argument("--repo-root", default=None)
    args = ap.parse_args(argv)

    from ruleset_analysis_tpu.verify import render_text, run_lint

    rep = run_lint(
        full=not args.fast,
        registry=not args.skip_registry,
        repo_root=args.repo_root,
    )
    print(json.dumps(rep.to_dict(), indent=2) if args.json else render_text(rep))
    return 0 if rep.ok else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
