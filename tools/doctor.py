#!/usr/bin/env python
"""Crash-forensics doctor: postmortem bundle + exit code -> diagnosis.

Usage:
    python tools/doctor.py POSTMORTEM.json [--exit-code RC]
                           [--lineage PATH] [--json]

The standalone twin of ``ruleset-analyze doctor`` (the logic lives in
``ruleset_analysis_tpu/runtime/flightrec.py::diagnose``; this wrapper
exists so a crashed box with only the repo checkout — no installed
entry point — can still be diagnosed).  Reads the ``postmortem.json``
an aborted run's flight recorder merged (``--blackbox-dir``, DESIGN
§20), ranks the likely causes against the documented exit-code classes
(README "Exit codes", 3-8), and prints the operator's next action.

For the timeline view of the same bundle, ``tools/trace_summary.py``
accepts a postmortem bundle directly and renders its ``blackbox`` block
(final-window stage occupancy, dump trigger, cursor positions).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ruleset_analysis_tpu.runtime import flightrec  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="ranked diagnosis from a crashed run's postmortem "
        "bundle (the first-response runbook for exit codes 3-8)"
    )
    ap.add_argument("bundle", help="postmortem.json, or the blackbox dir")
    ap.add_argument("--exit-code", type=int, default=None, metavar="RC",
                    help="the run's CLI exit code (default: from the bundle)")
    ap.add_argument("--lineage", default=None, metavar="PATH",
                    help="serve dir's lineage.jsonl to join with the bundle "
                         "(default: auto-detected beside the bundle); the "
                         "joined diagnosis names the last fully-published "
                         "window and the first missing/incomplete one")
    ap.add_argument("--json", action="store_true", help="machine output")
    args = ap.parse_args(argv)
    try:
        bundle = flightrec.load_bundle(args.bundle)
    except Exception as e:  # unreadable/foreign file: a clean error line
        print(f"error: unreadable postmortem bundle: {e}", file=sys.stderr)
        return 1
    lpath = args.lineage or flightrec.find_lineage(args.bundle)
    lineage = flightrec.load_lineage(lpath) if lpath else []
    diags = flightrec.diagnose(
        bundle, exit_code=args.exit_code, lineage=lineage
    )
    if args.json:
        from ruleset_analysis_tpu.runtime.report import lineage_frontier
        print(json.dumps({
            "trigger": bundle.get("trigger"),
            "exit_code": (
                args.exit_code if args.exit_code is not None
                else bundle.get("exit_code")
            ),
            "failing_stage": bundle.get("analysis", {}).get("failing_stage"),
            "lineage_path": lpath,
            "lineage_frontier": lineage_frontier(lineage) if lineage else None,
            "diagnosis": diags,
        }, indent=2))
    else:
        print(flightrec.render_diagnosis(bundle, diags))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
