"""North-star demonstration: 1e9+ lines through the FULL step on one chip.

BASELINE.json's north star is 1e9 ASA syslog lines/min end-to-end on a
v5e-8.  This run drives 1,000 chunks x 2^20 lines (1.049e9 lines) of
distinct resident wire-format batches through the complete registered
analysis step (match + exact counts + CMS + per-rule HLL + talker
sketch + candidate selection) on a SINGLE chip, closing the window with
the standard counts fetch: the final register total must equal the
exact number of valid lines fed, or the artifact is invalid.

Feeds are 16 distinct 1M-line batches resident in HBM (the packed
ingest tier keeps a real deployment fed at this rate from mmap'd wire
files; hostside feed decomposition is measured separately in bench.py's
e2e section) — this artifact isolates the DEVICE capability at the
north-star scale, not a microbenchmark: every register file is live and
the count check proves every chunk executed.

Writes NORTHSTAR_1E9_r05_tpu.json at the repo root.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    import jax

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import aclparse, pack, synth
    from ruleset_analysis_tpu.models import pipeline
    from ruleset_analysis_tpu.parallel import mesh as mesh_lib
    from ruleset_analysis_tpu.parallel.step import make_parallel_step
    from ruleset_analysis_tpu.runtime.compcache import enable_persistent_cache

    enable_persistent_cache()
    devices = jax.devices()
    platform = devices[0].platform
    rs = aclparse.parse_asa_config(
        synth.synth_config(n_acls=4, rules_per_acl=64, seed=0), "fw1"
    )
    packed = pack.pack_rulesets([rs])
    b = 1 << 20
    n_feeds = 16
    chunks = 1000
    cfg = AnalysisConfig(
        batch_size=b, sketch=SketchConfig(cms_width=1 << 14, cms_depth=4, hll_p=8)
    )
    mesh = mesh_lib.make_mesh(devices)
    step = make_parallel_step(mesh, cfg, packed.n_keys)
    rules = pipeline.ship_ruleset(packed)
    state = pipeline.init_state(packed.n_keys, cfg)

    feeds = []
    valid = []
    for i in range(n_feeds):
        t = np.ascontiguousarray(synth.synth_tuples(packed, b, seed=i).T)
        valid.append(int(t[pack.T_VALID].sum()))
        feeds.append(mesh_lib.shard_batch(mesh, pack.compact_batch(t)))
    print(f"{n_feeds} resident feeds x {b} lines", flush=True)

    for i in range(2):
        state, _ = step(state, rules, feeds[i % n_feeds], i)
    pipeline.sync_state(state)
    base = pipeline.counts_total(state)

    t0 = time.perf_counter()
    for i in range(chunks):
        # real chunk-salt discipline, like the stream driver
        state, _out = step(state, rules, feeds[i % n_feeds], i)
    total = pipeline.counts_total(state)  # sync closes the window
    dt = time.perf_counter() - t0

    lines = chunks * b
    expect = sum(valid[i % n_feeds] for i in range(chunks))
    delta = total - base
    ok = delta == expect
    lines_per_min = lines / dt * 60
    out = {
        "metric": "north_star_device_lines_1e9_single_chip",
        "value": round(lines / dt, 1),
        "unit": "lines/sec/chip",
        "vs_baseline": round((lines / dt) / (1e9 / 60 / 8), 4),
        "detail": {
            "platform": platform,
            "devices": len(devices),
            "total_lines": lines,
            "elapsed_sec": round(dt, 2),
            "lines_per_min_single_chip": round(lines_per_min, 1),
            "north_star_lines_per_min_8chip": 1e9,
            "single_chip_fraction_of_8chip_target": round(lines_per_min / 1e9, 4),
            "chunks": chunks,
            "batch": b,
            "resident_feeds": n_feeds,
            "counts_delta": delta,
            "counts_expected": expect,
            "counts_closed": ok,
            "registers_live": ["counts64", "cms", "hll", "talk_cms", "topk_candidates"],
        },
    }
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "NORTHSTAR_1E9_r05_tpu.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    if not (ok and platform == "tpu"):
        print("INVALID: counts mismatch or not on TPU", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
