#!/usr/bin/env python
"""Per-stage delta table between two devprof captures (DESIGN §14).

Usage:
    python tools/trace_diff.py A/devprof.json B/devprof.json [--json]

The evidence tool the scatter-wall work (ROADMAP item 2) and the two
stage-vs-step inversions (VERDICT Weak #2/#3) consume: run the SAME
workload twice under ``run --devprof-out`` with one knob changed
(``counts_impl=scatter`` vs ``matmul``, flat vs stacked, CPU vs TPU),
then diff the captures:

- **per-stage delta table** — device time per semantic stage
  (``ra.match``/``ra.counts``/...), normalized per profiled step so
  captures of different window lengths compare, with absolute and
  relative deltas.  A stage-level regression that an end-to-end number
  hides ("counts got faster but merge got slower") is one row here.
- **fusion-boundary change detection** — each capture records, per
  program, the set of semantic stages fused into every XLA fusion.
  Signatures present on one side only mean the compiler drew different
  fusion boundaries — the hypothesized mechanism behind both committed
  inversions, now checkable instead of smelled.

Accepts the ``devprof.json`` a capture writes (or a directory holding
one).  Classification comes from ``runtime/devprof.py`` — the same
classifier the in-process capture used, so the diff can never disagree
with the captures it compares.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def load_capture(path: str) -> dict:
    """One devprof.json (or a directory containing one) -> summary dict."""
    if os.path.isdir(path):
        path = os.path.join(path, "devprof.json")
    with open(path, "r", encoding="utf-8") as f:
        cap = json.load(f)
    if "stages" not in cap or "steps_profiled" not in cap:
        raise ValueError(f"{path!r} is not a devprof capture summary")
    cap["_path"] = path
    return cap


def _per_step(cap: dict, stage: str) -> float:
    steps = max(1, cap.get("steps_profiled", 1))
    return cap["stages"].get(stage, {}).get("device_us", 0.0) / steps


def _fusion_signatures(cap: dict) -> dict[str, set[tuple[str, ...]]]:
    """program label -> set of multi-instruction stage signatures.

    Single-stage fusions are kept too: a stage that WAS one fusion and
    became three is a boundary change even if no signature crosses
    stages.  Signatures count multiplicity via a trailing index so
    "two ra.counts fusions" differs from "one".
    """
    out: dict[str, set[tuple[str, ...]]] = {}
    for label, prog in (cap.get("programs") or {}).items():
        sigs: dict[tuple[str, ...], int] = {}
        for f in prog.get("fusions", []):
            key = tuple(f.get("stages") or ("(unscoped)",))
            sigs[key] = sigs.get(key, 0) + 1
        out[label] = {(*k, f"x{n}") for k, n in sigs.items()}
    return out


def diff_captures(a: dict, b: dict, label_a: str = "A", label_b: str = "B") -> dict:
    """Machine-readable per-stage delta + fusion-boundary changes."""
    stages = sorted(
        set(a["stages"]) | set(b["stages"]),
        key=lambda s: -(a["stages"].get(s, {}).get("device_us", 0.0)
                        + b["stages"].get(s, {}).get("device_us", 0.0)),
    )
    rows = []
    for s in stages:
        ua, ub = _per_step(a, s), _per_step(b, s)
        rows.append({
            "stage": s,
            f"{label_a}_us_per_step": round(ua, 1),
            f"{label_b}_us_per_step": round(ub, 1),
            "delta_us_per_step": round(ub - ua, 1),
            "ratio": round(ub / ua, 4) if ua > 0 else None,
            f"{label_a}_pct": a["stages"].get(s, {}).get("pct", 0.0),
            f"{label_b}_pct": b["stages"].get(s, {}).get("pct", 0.0),
        })
    tot_a = a.get("device_us_total", 0.0) / max(1, a.get("steps_profiled", 1))
    tot_b = b.get("device_us_total", 0.0) / max(1, b.get("steps_profiled", 1))
    sig_a, sig_b = _fusion_signatures(a), _fusion_signatures(b)
    boundary = {}
    for label in sorted(set(sig_a) | set(sig_b)):
        only_a = sorted(sig_a.get(label, set()) - sig_b.get(label, set()))
        only_b = sorted(sig_b.get(label, set()) - sig_a.get(label, set()))
        if only_a or only_b:
            boundary[label] = {
                f"only_{label_a}": [list(s) for s in only_a],
                f"only_{label_b}": [list(s) for s in only_b],
            }
    return {
        label_a: {
            "path": a.get("_path"),
            "label": a.get("label", ""),
            "steps_profiled": a.get("steps_profiled"),
            "backend": a.get("backend"),
            "attributed_frac": a.get("attributed_frac"),
            "step_us": round(tot_a, 1),
        },
        label_b: {
            "path": b.get("_path"),
            "label": b.get("label", ""),
            "steps_profiled": b.get("steps_profiled"),
            "backend": b.get("backend"),
            "attributed_frac": b.get("attributed_frac"),
            "step_us": round(tot_b, 1),
        },
        "step_ratio": round(tot_b / tot_a, 4) if tot_a > 0 else None,
        "stages": rows,
        "fusion_boundary_changes": boundary,
        "fusion_boundaries_changed": bool(boundary),
    }


def render(d: dict, label_a: str = "A", label_b: str = "B") -> str:
    ia, ib = d[label_a], d[label_b]

    def tag(info, fallback):
        return info.get("label") or os.path.basename(
            os.path.dirname(info.get("path") or "") or fallback
        ) or fallback

    na, nb = tag(ia, label_a), tag(ib, label_b)
    out = [
        f"== trace diff: {na} ({ia['backend']}, {ia['steps_profiled']} steps, "
        f"{100 * (ia['attributed_frac'] or 0):.1f}% attributed) vs "
        f"{nb} ({ib['backend']}, {ib['steps_profiled']} steps, "
        f"{100 * (ib['attributed_frac'] or 0):.1f}% attributed) ==",
        f"  step time: {ia['step_us']:.1f} -> {ib['step_us']:.1f} us/step "
        f"({d['step_ratio']}x)" if d["step_ratio"] is not None else
        f"  step time: {ia['step_us']:.1f} -> {ib['step_us']:.1f} us/step",
        f"  {'stage':<12} {na[:14]:>14} {nb[:14]:>14} {'delta':>12} {'ratio':>8}",
    ]
    ka, kb = f"{label_a}_us_per_step", f"{label_b}_us_per_step"
    for r in d["stages"]:
        ratio = f"{r['ratio']:.3f}x" if r["ratio"] is not None else "new"
        out.append(
            f"  {r['stage']:<12} {r[ka]:>12.1f}us {r[kb]:>12.1f}us "
            f"{r['delta_us_per_step']:>+10.1f}us {ratio:>8}"
        )
    bc = d["fusion_boundary_changes"]
    if bc:
        out.append("  fusion boundaries CHANGED:")
        for label, ch in bc.items():
            for side, sigs in ch.items():
                for s in sigs:
                    out.append(f"    {label}: {side}: {'+'.join(s)}")
    else:
        out.append("  fusion boundaries: unchanged")
    return "\n".join(out)


def render_csv(d: dict, label_a: str = "A", label_b: str = "B") -> str:
    """Spreadsheet-ready stage table (one header + one row per stage,
    plus a ``(step)`` totals row).  Fusion-boundary changes are not
    tabular — use ``--json`` for those; the boundary VERDICT rides the
    totals row's last column so a CSV consumer still sees it.
    """
    import csv
    import io

    ka, kb = f"{label_a}_us_per_step", f"{label_b}_us_per_step"
    buf = io.StringIO()
    w = csv.writer(buf, lineterminator="\n")
    w.writerow([
        "stage", ka, kb, "delta_us_per_step", "ratio",
        f"{label_a}_pct", f"{label_b}_pct", "fusion_boundaries_changed",
    ])
    for r in d["stages"]:
        w.writerow([
            r["stage"], r[ka], r[kb], r["delta_us_per_step"],
            "" if r["ratio"] is None else r["ratio"],
            r[f"{label_a}_pct"], r[f"{label_b}_pct"], "",
        ])
    w.writerow([
        "(step)", d[label_a]["step_us"], d[label_b]["step_us"],
        round(d[label_b]["step_us"] - d[label_a]["step_us"], 1),
        "" if d["step_ratio"] is None else d["step_ratio"],
        100.0, 100.0, d["fusion_boundaries_changed"],
    ])
    return buf.getvalue()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="per-stage delta table between two devprof captures"
    )
    ap.add_argument("old", help="baseline capture (devprof.json or its dir)")
    ap.add_argument("new", help="comparison capture")
    fmt = ap.add_mutually_exclusive_group()
    fmt.add_argument("--json", action="store_true", help="machine output")
    fmt.add_argument("--csv", action="store_true",
                     help="stage table as CSV (README: reading a trace diff)")
    args = ap.parse_args(argv)
    try:
        a, b = load_capture(args.old), load_capture(args.new)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    d = diff_captures(a, b)
    if args.json:
        print(json.dumps(d, indent=2))
    elif args.csv:
        print(render_csv(d), end="")
    else:
        print(render(d))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
