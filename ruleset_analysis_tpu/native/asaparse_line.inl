// The ASA line parser body, compiled ONCE PER ISA (ISSUE 11).
//
// Includers must define, BEFORE including this file:
//
//   RA_PARSE_NS            namespace for this build (ra_scalar/ra_avx2/…)
//   ra_scan_token_end(p, end)   end of the maximal non-whitespace run
//   ra_scan_addr_end(p, end)    end of the maximal [0-9A-Fa-f:.] run
//   ra_scan_ipv4(&p, end, out)  dotted-quad fast parse: 1 proven parse
//                               (value in *out, p advanced), 0 proven
//                               reject, -1 defer to the scalar reference
//
// as file-local inline functions, so the compiler inlines the ISA's
// scan kernels straight into the token/endpoint scanners — a function
// call per 10-byte token was measured to cost more than the vector math
// saved (0.93-0.95x), which is why dispatch happens per LINE (one
// indirect call amortized over ~10 scans), not per scan.
//
// Parse semantics are identical in every build; the 12k mutant sweep in
// tests/test_fastparse.py pins scalar == SIMD byte-for-byte.

namespace ra_parse {
namespace RA_PARSE_NS {

constexpr int64_t TUPLE_COLS = 7;

inline bool is_sp(char c) { return c == ' ' || c == '\t' || c == '\v' || c == '\f' || c == '\r' || c == '\n'; }
inline bool is_dig(char c) { return c >= '0' && c <= '9'; }

inline const char* find_sub(const char* p, const char* end, const char* pat, size_t n) {
    if (end - p < (std::ptrdiff_t)n) return nullptr;
    return (const char*)memmem(p, end - p, pat, n);
}

// Parse a decimal run; false if no digits or value > 2^32-1.
inline bool parse_u32(const char*& p, const char* end, uint32_t* out) {
    if (p >= end || !is_dig(*p)) return false;
    uint64_t v = 0;
    const char* q = p;
    while (q < end && is_dig(*q)) {
        v = v * 10 + (uint64_t)(*q - '0');
        if (v > 0xFFFFFFFFull) return false;
        ++q;
    }
    *out = (uint32_t)v;
    p = q;
    return true;
}

inline bool is_hex(char c) {
    return is_dig(c) || (c >= 'a' && c <= 'f') || (c >= 'A' && c <= 'F');
}
inline bool is_addr_char(char c) { return is_hex(c) || c == ':' || c == '.'; }
inline uint32_t hex_val(char c) {
    if (is_dig(c)) return (uint32_t)(c - '0');
    if (c >= 'a' && c <= 'f') return (uint32_t)(c - 'a' + 10);
    return (uint32_t)(c - 'A' + 10);
}

// Dotted-quad IPv4 over a [0-9.] run: exactly 4 octets, each 0..255
// (hostside.aclparse.ip_to_u32 semantics).  Advances p past the run on
// success; on failure leaves p unspecified and returns false.
inline bool parse_ipv4_run(const char*& p, const char* end, uint32_t* out) {
    {
        // ISA fast path: only verdicts the scalar reference below would
        // reach are allowed; -1 defers (scalar builds always defer)
        int r = ra_scan_ipv4(&p, end, out);
        if (r >= 0) return r == 1;
    }
    uint32_t v = 0;
    int octets = 0;
    const char* q = p;
    while (octets < 4) {
        if (q >= end || !is_dig(*q)) return false;
        uint64_t o = 0;
        while (q < end && is_dig(*q)) {
            o = o * 10 + (uint64_t)(*q - '0');
            if (o > 0xFFFFFFFFull) return false;
            ++q;
        }
        if (o > 255) return false;
        v = (v << 8) | (uint32_t)o;
        ++octets;
        if (octets < 4) {
            if (q >= end || *q != '.') return false;
            ++q;
        }
    }
    // the regex run [\d.]+ is maximal: a trailing '.' or digit means the
    // run does not parse as exactly four octets
    if (q < end && (*q == '.' || is_dig(*q))) return false;
    *out = v;
    p = q;
    return true;
}

// One parsed address of either family: fam is 4 or 6; v6 addresses carry
// 4 big-endian uint32 limbs (pack.u128_limbs layout).
struct Addr {
    uint32_t fam = 4;
    uint32_t v4 = 0;
    uint32_t l[4] = {0, 0, 0, 0};
};

// Parse [rs, re) — one complete address text run — as an IPv6 literal
// (RFC 4291 forms: hex groups, one '::' compression, optional embedded
// trailing dotted quad).  Mirrors the stdlib ipaddress acceptance the
// Python path delegates to (hostside.aclparse.ip6_to_int): groups are
// 1-4 hex digits, exactly 8 groups without '::', fewer with, the
// embedded v4 counts as two groups and may only appear last.
inline bool parse_ipv6_text(const char* rs, const char* re, uint32_t limbs[4]) {
    uint16_t head[8];
    uint16_t tail[8];
    int n_head = 0, n_tail = 0;
    bool compressed = false;
    const char* p = rs;
    if (p >= re) return false;
    if (*p == ':') {
        // must be a leading '::'
        if (p + 1 >= re || p[1] != ':') return false;
        compressed = true;
        p += 2;
    }
    bool want_group = !(compressed && p == re);
    while (p < re) {
        // embedded trailing dotted quad? detect a digit run followed by '.'
        const char* q = p;
        while (q < re && is_dig(*q)) ++q;
        if (q > p && q < re && *q == '.') {
            const char* v4p = p;
            uint32_t v4;
            if (!parse_ipv4_run(v4p, re, &v4) || v4p != re) return false;
            uint16_t* dst = compressed ? tail : head;
            int& n = compressed ? n_tail : n_head;
            if (n + 2 > 8) return false;
            dst[n++] = (uint16_t)(v4 >> 16);
            dst[n++] = (uint16_t)(v4 & 0xFFFF);
            p = re;
            want_group = false;
            break;
        }
        // hex group: 1-4 hex digits
        uint32_t g = 0;
        int nd = 0;
        while (p < re && is_hex(*p) && nd < 5) {
            g = (g << 4) | hex_val(*p);
            ++p;
            ++nd;
        }
        if (nd == 0 || nd > 4) return false;
        uint16_t* dst = compressed ? tail : head;
        int& n = compressed ? n_tail : n_head;
        if (n >= 8) return false;
        dst[n++] = (uint16_t)g;
        want_group = false;
        if (p < re) {
            if (*p != ':') return false;
            ++p;
            if (p < re && *p == ':') {
                if (compressed) return false;  // second '::'
                compressed = true;
                ++p;
                if (p == re) { want_group = false; break; }
            } else {
                if (p == re) return false;  // single trailing ':'
                want_group = true;
            }
        }
    }
    if (want_group) return false;
    int total = n_head + n_tail;
    if (compressed ? total >= 8 : total != 8) return false;
    uint16_t groups[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    for (int i = 0; i < n_head; ++i) groups[i] = head[i];
    for (int i = 0; i < n_tail; ++i) groups[8 - n_tail + i] = tail[i];
    for (int i = 0; i < 4; ++i)
        limbs[i] = ((uint32_t)groups[2 * i] << 16) | groups[2 * i + 1];
    return true;
}

// Parse the maximal [0-9A-Fa-f:.] run at p as an address of either
// family (the Python regexes capture exactly this class and then parse
// by ':' presence).  Returns 1 on success (p past the run), 0 when the
// run is not address-shaped at all (structural failure — caller keeps
// scanning), -1 when the run IS the address capture but its value is
// invalid (semantic failure: Python raises inside _addr and the whole
// line skips with no rescan).
inline int parse_addr_run(const char*& p, const char* end, Addr* a) {
    const char* rs = p;
    const char* re = ra_scan_addr_end(rs, end);
    if (re == rs) return 0;
    bool has_colon = memchr(rs, ':', (size_t)(re - rs)) != nullptr;
    if (!has_colon) {
        const char* q = rs;
        uint32_t v4;
        if (!parse_ipv4_run(q, re, &v4) || q != re) return -1;
        a->fam = 4;
        a->v4 = v4;
        p = re;
        return 1;
    }
    if (!parse_ipv6_text(rs, re, a->l)) return -1;
    a->fam = 6;
    p = re;
    return 1;
}

inline void skip_ws(const char*& p, const char* end) {
    while (p < end && is_sp(*p)) ++p;
}

inline bool skip_ws1(const char*& p, const char* end) {  // require at least one
    if (p >= end || !is_sp(*p)) return false;
    skip_ws(p, end);
    return true;
}

// Token = maximal non-space run.
inline bool token(const char*& p, const char* end, const char** t0, const char** t1) {
    if (p >= end || is_sp(*p)) return false;
    *t0 = p;
    p = ra_scan_token_end(p + 1, end);  // first char already known non-space
    *t1 = p;
    return true;
}

inline bool tok_eq(const char* t0, const char* t1, const char* s) {
    size_t n = strlen(s);
    return (size_t)(t1 - t0) == n && memcmp(t0, s, n) == 0;
}

// _proto_num: PROTO_NUMBERS name (case-insensitive) -> number; else
// decimal; else 0.
inline uint32_t proto_num(const char* t0, const char* t1) {
    char buf[16];
    size_t n = (size_t)(t1 - t0);
    if (n < sizeof(buf)) {
        for (size_t i = 0; i < n; ++i) {
            char c = t0[i];
            buf[i] = (c >= 'A' && c <= 'Z') ? (char)(c + 32) : c;
        }
        buf[n] = 0;
        // ordered by real-traffic frequency: tcp/udp dominate ASA logs
        struct { const char* name; uint32_t v; } static const tbl[] = {
            {"tcp", 6},  {"udp", 17},  {"icmp", 1},  {"ip", 0},
            {"igmp", 2}, {"ipinip", 4}, {"gre", 47},  {"esp", 50},
            {"ah", 51},  {"icmp6", 58}, {"eigrp", 88}, {"ospf", 89},
            {"nos", 94}, {"pim", 103}, {"pcp", 108}, {"snp", 109},
            {"sctp", 132},
        };
        for (auto& e : tbl)
            if (strcmp(buf, e.name) == 0) return e.v;
    }
    const char* p = t0;
    uint32_t v = 0;
    if (parse_u32(p, t1, &v) && p == t1) return v;
    return 0;
}

struct Parsed {
    const char* fw0; const char* fw1;
    const char* acl0; const char* acl1;   // acl0 == nullptr: resolve by iface
    const char* if0; const char* if1;     // ingress interface (in binding)
    const char* eif0 = nullptr;           // egress interface (out binding);
    const char* eif1 = nullptr;           // 302013/302015 only
    uint32_t proto, sport, dport;
    Addr src, dst;                        // either family; must agree
};

// "if/ADDR(port)" endpoint of 106100: iface is the shortest prefix whose
// '/' is followed by a parseable "ADDR(port)" of either family.
// Returns 1 ok / 0 structural mismatch (caller keeps scanning) /
// -1 semantic failure (address text captured but invalid — Python raises
// inside _addr and the whole line skips, so callers must abort).
inline int endpoint_slash_paren(const char*& p, const char* end,
                                const char** if0, const char** if1,
                                Addr* addr, uint32_t* port) {
    const char* t0; const char* t1;
    const char* q = p;
    if (!token(q, end, &t0, &t1)) return 0;
    for (const char* s = t0; s < t1; ++s) {
        if (*s != '/') continue;
        if (s == t0) continue;  // iface must be non-empty
        const char* c = s + 1;
        // structure first: maximal addr run, then '(digits)'
        const char* re = ra_scan_addr_end(c, t1);
        if (re == c || re >= t1 || *re != '(') continue;
        const char* pc = re + 1;
        uint32_t pv;
        if (!parse_u32(pc, t1, &pv)) continue;
        if (pc >= t1 || *pc != ')') continue;
        ++pc;
        Addr a;
        const char* ac = c;
        if (parse_addr_run(ac, re, &a) != 1 || ac != re) return -1;
        *if0 = t0; *if1 = s; *addr = a; *port = pv;
        p = pc;  // just past ')': an extra paren group may follow unspaced
        return 1;
    }
    return 0;
}

// "if:ADDR[/port]" endpoint of 106023 (port optional, defaults 0) and
// 302013 (port required).  Same 1/0/-1 contract as endpoint_slash_paren.
//
// ``require_token_end``: the 106023 SRC endpoint is followed by ``\s+dst``
// in the regex, so Python only commits to a colon split whose endpoint
// reaches the end of the token — a mid-token leftover is a STRUCTURAL
// mismatch that backtracks to a later colon (fuzz: "inside:1side:A.B.C.D"
// must split at the SECOND colon).  The DST endpoint is followed by
// ``.*?by`` (anything matches), so it commits to the first structural
// split and a bad value there skips the line — require_token_end=false.
inline int endpoint_colon(const char*& p, const char* end, bool port_required,
                          const char** if0, const char** if1,
                          Addr* addr, uint32_t* port,
                          bool require_token_end = false) {
    const char* t0; const char* t1;
    const char* q = p;
    if (!token(q, end, &t0, &t1)) return 0;
    for (const char* s = t0; s < t1; ++s) {
        if (*s != ':') continue;
        if (s == t0) continue;
        const char* c = s + 1;
        const char* re = ra_scan_addr_end(c, t1);
        if (re == c) continue;
        uint32_t pv = 0;
        const char* after = re;
        if (after < t1 && *after == '/') {
            const char* c2 = after + 1;
            if (parse_u32(c2, t1, &pv)) after = c2;
            else if (port_required) continue;
        } else if (port_required) {
            continue;
        }
        if (require_token_end && after != t1) continue;
        Addr a;
        const char* ac = c;
        if (parse_addr_run(ac, re, &a) != 1 || ac != re) return -1;
        *if0 = t0; *if1 = s; *addr = a; *port = pv;
        p = after;
        return 1;
    }
    return 0;
}

inline bool parse_106100(const char* b, const char* be, Parsed* out) {
    const char* pos = b;
    while (true) {
        const char* hit = find_sub(pos, be, "access-list", 11);
        if (!hit) return false;
        pos = hit + 1;
        const char* p = hit + 11;
        const char* a0; const char* a1; const char* v0; const char* v1;
        const char* pr0; const char* pr1;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &a0, &a1)) continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &v0, &v1)) continue;
        if (!(tok_eq(v0, v1, "permitted") || tok_eq(v0, v1, "denied") ||
              tok_eq(v0, v1, "est-allowed")))
            continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &pr0, &pr1)) continue;
        if (!skip_ws1(p, be)) continue;
        const char* i0; const char* i1; Addr sa; uint32_t spo;
        int rc = endpoint_slash_paren(p, be, &i0, &i1, &sa, &spo);
        if (rc < 0) return false;  // invalid address text: line skips
        if (!rc) continue;
        if (p < be && *p == '(') {  // optional "(...)" (e.g. identity info)
            const char* c = (const char*)memchr(p, ')', be - p);
            if (c) p = c + 1;
        }
        skip_ws(p, be);
        if (p + 1 >= be || p[0] != '-' || p[1] != '>') continue;
        p += 2;
        skip_ws(p, be);
        const char* j0; const char* j1; Addr da; uint32_t dpo;
        rc = endpoint_slash_paren(p, be, &j0, &j1, &da, &dpo);
        if (rc < 0) return false;
        if (!rc) continue;
        if (sa.fam != da.fam) return false;  // mixed-family line: skip
        uint32_t proto = proto_num(pr0, pr1);
        // ICMP/ICMPv6: parenthesised values are type/code; type -> dport,
        // sport=0 (58 added with the v6 data model; mirrors syslog.py)
        if (proto == 1 || proto == 58) { dpo = spo; spo = 0; }
        out->acl0 = a0; out->acl1 = a1;
        out->if0 = i0; out->if1 = i1;
        out->proto = proto; out->src = sa; out->sport = spo;
        out->dst = da; out->dport = dpo;
        return true;
    }
}

inline bool parse_106023(const char* b, const char* be, Parsed* out) {
    const char* pos = b;
    while (true) {
        const char* hit = find_sub(pos, be, "Deny", 4);
        if (!hit) return false;
        pos = hit + 1;
        const char* p = hit + 4;
        const char* pr0; const char* pr1; const char* s0; const char* s1;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &pr0, &pr1)) continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &s0, &s1) || !tok_eq(s0, s1, "src")) continue;
        if (!skip_ws1(p, be)) continue;
        const char* i0; const char* i1; Addr sa; uint32_t spo;
        int rc = endpoint_colon(p, be, false, &i0, &i1, &sa, &spo,
                                /*require_token_end=*/true);
        if (rc < 0) return false;
        if (!rc) continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &s0, &s1) || !tok_eq(s0, s1, "dst")) continue;
        if (!skip_ws1(p, be)) continue;
        const char* j0; const char* j1; Addr da; uint32_t dpo;
        rc = endpoint_colon(p, be, false, &j0, &j1, &da, &dpo);
        if (rc < 0) return false;
        if (!rc) continue;
        if (sa.fam != da.fam) return false;
        // optional " (type T, code C)"
        bool have_type = false;
        uint32_t icmp_type = 0, tmp;
        {
            const char* q = p;
            if (skip_ws1(q, be) && q + 5 <= be && memcmp(q, "(type", 5) == 0) {
                const char* c = q + 5;
                if (skip_ws1(c, be) && parse_u32(c, be, &icmp_type) &&
                    c < be && *c == ',') {
                    ++c;
                    skip_ws(c, be);
                    if (c + 4 <= be && memcmp(c, "code", 4) == 0) {
                        c += 4;
                        if (skip_ws1(c, be) && parse_u32(c, be, &tmp) &&
                            c < be && *c == ')') {
                            have_type = true;
                            p = c + 1;
                        }
                    }
                }
            }
        }
        // .*?by\s+access-group\s+"<acl>"
        const char* scan = p;
        const char* a0 = nullptr; const char* a1 = nullptr;
        while (true) {
            const char* ag = find_sub(scan, be, "access-group", 12);
            if (!ag) break;
            scan = ag + 1;
            const char* back = ag;
            if (back <= p || !is_sp(back[-1])) continue;
            while (back > p && is_sp(back[-1])) --back;
            if (back - p < 2 || back[-1] != 'y' || back[-2] != 'b') continue;
            const char* c = ag + 12;
            if (!skip_ws1(c, be)) continue;
            if (c >= be || *c != '"') continue;
            ++c;
            const char* close = (const char*)memchr(c, '"', be - c);
            if (!close || close == c) continue;  // regex [^"]+ needs >=1 char
            a0 = c; a1 = close;
            break;
        }
        if (!a0) continue;
        uint32_t proto = proto_num(pr0, pr1);
        if ((proto == 1 || proto == 58) && have_type) { dpo = icmp_type; spo = 0; }
        out->acl0 = a0; out->acl1 = a1;
        out->if0 = i0; out->if1 = i1;
        out->proto = proto; out->src = sa; out->sport = spo;
        out->dst = da; out->dport = dpo;
        return true;
    }
}

inline bool parse_302013(const char* b, const char* be, Parsed* out) {
    const char* pos = b;
    while (true) {
        const char* hit = find_sub(pos, be, "Built", 5);
        if (!hit) return false;
        pos = hit + 1;
        const char* p = hit + 5;
        const char* t0; const char* t1;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &t0, &t1)) continue;
        bool inbound;
        if (tok_eq(t0, t1, "inbound")) inbound = true;
        else if (tok_eq(t0, t1, "outbound")) inbound = false;
        else continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &t0, &t1)) continue;
        uint32_t proto;
        if (tok_eq(t0, t1, "TCP")) proto = 6;
        else if (tok_eq(t0, t1, "UDP")) proto = 17;
        else continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "connection")) continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &t0, &t1)) continue;  // connection id
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "for")) continue;
        if (!skip_ws1(p, be)) continue;
        const char* ia0; const char* ia1; Addr aa; uint32_t poa;
        int rc = endpoint_colon(p, be, true, &ia0, &ia1, &aa, &poa);
        if (rc < 0) return false;
        if (!rc) continue;
        skip_ws(p, be);
        if (p < be && *p == '(') {
            const char* c = (const char*)memchr(p, ')', be - p);
            if (c) p = c + 1;
        }
        skip_ws(p, be);
        if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "to")) continue;
        if (!skip_ws1(p, be)) continue;
        const char* ib0; const char* ib1; Addr ab; uint32_t pob;
        rc = endpoint_colon(p, be, true, &ib0, &ib1, &ab, &pob);
        if (rc < 0) return false;
        if (!rc) continue;
        if (aa.fam != ab.fam) return false;
        out->acl0 = nullptr; out->acl1 = nullptr;
        // inbound: initiated at A (src=A, ingress=ifA, egress=ifB);
        // outbound: initiated at B (src=B, ingress=ifB, egress=ifA).
        // The egress side's out-direction ACL (if bound) also filters.
        if (inbound) {
            out->if0 = ia0; out->if1 = ia1;
            out->eif0 = ib0; out->eif1 = ib1;
            out->src = aa; out->sport = poa; out->dst = ab; out->dport = pob;
        } else {
            out->if0 = ib0; out->if1 = ib1;
            out->eif0 = ia0; out->eif1 = ia1;
            out->src = ab; out->sport = pob; out->dst = aa; out->dport = poa;
        }
        out->proto = proto;
        return true;
    }
}

// "ADDR/port" endpoint of the 106001/106006/106015 family ("from A/p to
// B/q"): a bare address of either family, '/', decimal port — no
// interface prefix.  Same 1/0/-1 contract as the other endpoints.
inline int endpoint_bare(const char*& p, const char* end, Addr* addr, uint32_t* port) {
    const char* re = ra_scan_addr_end(p, end);
    if (re == p) return 0;
    if (re >= end || *re != '/') return 0;
    const char* q = re + 1;
    uint32_t pv;
    if (!parse_u32(q, end, &pv)) return 0;
    Addr a;
    const char* ac = p;
    if (parse_addr_run(ac, re, &a) != 1 || ac != re) return -1;
    *addr = a; *port = pv;
    p = q;
    return 1;
}

// First "on interface <if>" at or after p (the 106001/106015 regexes use
// a lazy ".*?", so the FIRST occurrence wins, matching syslog.py).
inline bool on_interface_scan(const char* p, const char* be, const char** i0, const char** i1) {
    const char* scan = p;
    while (true) {
        const char* hit = find_sub(scan, be, "on", 2);
        if (!hit) return false;
        scan = hit + 1;
        // \bon: previous char must not be a word char (regex \b semantics)
        char prev = hit > p ? hit[-1] : ' ';
        if ((prev >= 'a' && prev <= 'z') || (prev >= 'A' && prev <= 'Z') ||
            (prev >= '0' && prev <= '9') || prev == '_')
            continue;
        const char* c = hit + 2;
        if (!skip_ws1(c, be)) continue;
        const char* t0; const char* t1;
        if (!token(c, be, &t0, &t1) || !tok_eq(t0, t1, "interface")) continue;
        if (!skip_ws1(c, be)) continue;
        if (!token(c, be, &t0, &t1)) continue;
        *i0 = t0; *i1 = t1;
        return true;
    }
}

// 106001: Inbound TCP connection denied from A/p to B/q flags ... on
// interface IF.  106015: Deny TCP (no connection) from A/p to B/q flags
// ... on interface IF.  106006: Deny inbound UDP from A/p to B/q on
// interface IF (immediately — no flags text).  All resolve via the
// interface's in-direction binding.  ``lead`` is a token sequence matched
// with \s+ separators (the regexes' flexibility); a token prefixed with
// '\x01' must instead be separated from its predecessor by EXACTLY one
// space (the 106015 pattern embeds a literal space inside
// "\(no connection\)").
inline bool parse_106001_like(const char* b, const char* be,
                              const char* const* lead, int lead_n,
                              bool need_flags, uint32_t proto, Parsed* out) {
    size_t first_n = strlen(lead[0]);
    const char* pos = b;
    while (true) {
        const char* hit = find_sub(pos, be, lead[0], first_n);
        if (!hit) return false;
        pos = hit + 1;
        const char* p = hit;
        const char* t0; const char* t1;
        bool lead_ok = true;
        for (int i = 0; i < lead_n; ++i) {
            const char* want = lead[i];
            if (i) {
                if (want[0] == '\x01') {
                    ++want;
                    if (p >= be || *p != ' ') { lead_ok = false; break; }
                    ++p;  // exactly one space; token() rejects a second
                } else if (!skip_ws1(p, be)) {
                    lead_ok = false;
                    break;
                }
            }
            if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, want)) {
                lead_ok = false;
                break;
            }
        }
        if (!lead_ok) continue;
        if (!skip_ws1(p, be)) continue;
        Addr sa; uint32_t spo;
        int rc = endpoint_bare(p, be, &sa, &spo);
        if (rc < 0) return false;
        if (!rc) continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "to")) continue;
        if (!skip_ws1(p, be)) continue;
        Addr da; uint32_t dpo;
        rc = endpoint_bare(p, be, &da, &dpo);
        if (rc < 0) return false;
        if (!rc) continue;
        if (sa.fam != da.fam) return false;
        const char* i0; const char* i1;
        if (need_flags) {
            if (!skip_ws1(p, be)) continue;
            if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "flags")) continue;
            if (!on_interface_scan(p, be, &i0, &i1)) continue;
        } else {
            // 106006: "on interface" must follow the endpoints directly
            if (!skip_ws1(p, be)) continue;
            if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "on")) continue;
            if (!skip_ws1(p, be)) continue;
            if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "interface")) continue;
            if (!skip_ws1(p, be)) continue;
            if (!token(p, be, &i0, &i1)) continue;
        }
        out->acl0 = nullptr; out->acl1 = nullptr;
        out->if0 = i0; out->if1 = i1;
        out->proto = proto;
        out->src = sa; out->sport = spo; out->dst = da; out->dport = dpo;
        return true;
    }
}

// Parse one line; emit its ACL evaluations into the column-major output.
//
// Returns the number of tuple rows written (0 = line skipped), or -1 when
// the line's rows do NOT fit in [row, cap) — the caller must close the
// batch without consuming the line.  A connection message whose ingress
// interface has an in-ACL and whose egress interface has an out-ACL emits
// TWO rows (two independent evaluations), mirroring LinePacker.
//
// Parity note (syslog.parse_line): _TAG_RE.search finds the FIRST
// well-formed "%ASA-<d>-<dddddd>:" marker that has a host token before
// it; the line's fate is then decided by that one tag — an unhandled
// msgid or a failed body parse means the line is skipped, with no retry
// against later markers.  Only malformed markers keep the scan going.
int handle_line(LocalCtx* pk, const char* ls, const char* le,
                uint32_t* out, int64_t cap, int64_t row,
                uint32_t* out6, int64_t cap6, int64_t* row6) {
    const char* pos = ls;
    const char* msgid = nullptr;
    const char* body = nullptr;
    const char* h0 = nullptr; const char* h1 = nullptr;
    while (true) {
        const char* tag = find_sub(pos, le, "%ASA-", 5);
        if (!tag) return 0;
        pos = tag + 1;
        const char* t = tag + 5;
        if (t >= le || !is_dig(*t)) continue;
        ++t;
        if (t >= le || *t != '-') continue;
        ++t;
        const char* mid = t;
        int nd = 0;
        while (t < le && is_dig(*t) && nd < 7) { ++t; ++nd; }
        if (nd != 6 || t >= le || *t != ':') continue;

        // host: last token (one optional trailing ':') before the marker
        const char* q = tag;
        while (q > ls && is_sp(q[-1])) --q;
        if (q > ls && q[-1] == ':') {
            --q;
            while (q > ls && is_sp(q[-1])) --q;
        }
        const char* he = q;
        while (q > ls && !is_sp(q[-1])) --q;
        if (he == q) continue;  // no host token; try a later marker

        msgid = mid;
        body = t + 1;
        skip_ws(body, le);
        h0 = q; h1 = he;
        break;
    }

    Parsed pr;
    bool ok;
    if (memcmp(msgid, "106100", 6) == 0) ok = parse_106100(body, le, &pr);
    else if (memcmp(msgid, "106023", 6) == 0) ok = parse_106023(body, le, &pr);
    else if (memcmp(msgid, "302013", 6) == 0 || memcmp(msgid, "302015", 6) == 0)
        ok = parse_302013(body, le, &pr);
    else if (memcmp(msgid, "106001", 6) == 0) {
        static const char* const lead[] = {
            "Inbound", "TCP", "connection", "denied", "from"};
        ok = parse_106001_like(body, le, lead, 5, /*need_flags=*/true, 6, &pr);
    } else if (memcmp(msgid, "106015", 6) == 0) {
        static const char* const lead[] = {
            // "\001" (octal): "\x01c..." would munch the 'c' as a hex digit
            "Deny", "TCP", "(no", "\001connection)", "from"};
        ok = parse_106001_like(body, le, lead, 5, /*need_flags=*/true, 6, &pr);
    } else if (memcmp(msgid, "106006", 6) == 0) {
        static const char* const lead[] = {"Deny", "inbound", "UDP", "from"};
        ok = parse_106001_like(body, le, lead, 4, /*need_flags=*/false, 17, &pr);
    } else return 0;  // unhandled message class
    if (!ok) return 0;
    // wire-width validation (syslog.py _field_ranges_ok): ports are
    // 16-bit, protocol numbers 8-bit; a line claiming more is malformed
    // and skipping beats silently truncating it into a false match
    if (pr.sport > 0xFFFF || pr.dport > 0xFFFF || pr.proto > 0xFF) return 0;

    // resolve into up to two gids: named ACL, or in-binding of the
    // ingress interface plus out-binding of the egress interface
    std::string& k = pk->keybuf;
    uint32_t gids[2];
    int n_gids = 0;
    if (pr.acl0) {
        k.assign(h0, h1 - h0);
        k.push_back('\x01');
        k.append(pr.acl0, pr.acl1 - pr.acl0);
        auto it = pk->resolve->find(k);
        if (it != pk->resolve->end()) gids[n_gids++] = it->second;
    } else {
        k.assign(h0, h1 - h0);
        k.push_back('\x02');
        k.append(pr.if0, pr.if1 - pr.if0);
        auto it = pk->resolve->find(k);
        if (it != pk->resolve->end()) gids[n_gids++] = it->second;
        if (pr.eif0) {
            k.assign(h0, h1 - h0);
            k.push_back('\x03');
            k.append(pr.eif0, pr.eif1 - pr.eif0);
            it = pk->resolve->find(k);
            if (it != pk->resolve->end()) gids[n_gids++] = it->second;
        }
    }
    if (n_gids == 0) return 0;
    if (pr.src.fam == 6) {
        // v6 line: rows land in the [TUPLE6_COLS=13, cap6] side plane
        // (mirrors LinePacker.pack_parsed2 / _TextSource staging); a v6
        // line against a pure-v4 ruleset is a counted skip
        if (!out6 || !row6) return 0;
        int64_t r6 = *row6;
        if (r6 + n_gids > cap6) return -1;
        for (int g = 0; g < n_gids; ++g, ++r6) {
            out6[0 * cap6 + r6] = gids[g];
            out6[1 * cap6 + r6] = pr.proto;
            for (int i = 0; i < 4; ++i) out6[(2 + i) * cap6 + r6] = pr.src.l[i];
            out6[6 * cap6 + r6] = pr.sport;
            for (int i = 0; i < 4; ++i) out6[(7 + i) * cap6 + r6] = pr.dst.l[i];
            out6[11 * cap6 + r6] = pr.dport;
            out6[12 * cap6 + r6] = 1;
        }
        *row6 = r6;
        return n_gids;
    }
    if (row + n_gids > cap) return -1;  // close the batch; line unconsumed
    for (int g = 0; g < n_gids; ++g, ++row) {
        out[0 * cap + row] = gids[g];
        out[1 * cap + row] = pr.proto;
        out[2 * cap + row] = pr.src.v4;
        out[3 * cap + row] = pr.sport;
        out[4 * cap + row] = pr.dst.v4;
        out[5 * cap + row] = pr.dport;
        out[6 * cap + row] = 1;
    }
    return n_gids;
}

}  // namespace RA_PARSE_NS
}  // namespace ra_parse
