// NEON build of the ASA line parser (aarch64).
//
// NEON is baseline on aarch64 — the guard is compile-time only.  Mask
// extraction uses the vshrn_n_u16 narrowing trick (a 64-bit nibble mask
// per 16-byte block).  The same inline-into-the-tokenizer structure and
// no-read-past-end discipline as the AVX2 TU apply.

#include "asaparse_types.h"

#if defined(__ARM_NEON) || defined(__aarch64__)

#include <arm_neon.h>

#include <cstring>

namespace {

inline bool sc_is_sp(char c) {
    return c == ' ' || c == '\t' || c == '\v' || c == '\f' || c == '\r' ||
           c == '\n';
}
inline bool sc_is_dig(char c) { return c >= '0' && c <= '9'; }
inline bool sc_is_addr(char c) {
    return sc_is_dig(c) || (c >= 'a' && c <= 'f') || (c >= 'A' && c <= 'F') ||
           c == ':' || c == '.';
}

// 4 bits per byte lane: nibble i of the result covers lane i
inline uint64_t nibble_mask(uint8x16_t eq) {
    return vget_lane_u64(
        vreinterpret_u64_u8(vshrn_n_u16(vreinterpretq_u16_u8(eq), 4)), 0);
}

inline uint8x16_t in_range(uint8x16_t v, uint8_t lo, uint8_t span) {
    return vcleq_u8(vsubq_u8(v, vdupq_n_u8(lo)), vdupq_n_u8(span));
}

inline const char* ra_scan_addr_end(const char* p, const char* end) {
    while (p + 16 <= end) {
        uint8x16_t v = vld1q_u8((const uint8_t*)p);
        uint8x16_t ok = vorrq_u8(
            vorrq_u8(in_range(v, 0x30, 0x0A), in_range(v, 0x41, 5)),
            vorrq_u8(in_range(v, 0x61, 5), vceqq_u8(v, vdupq_n_u8('.'))));
        uint64_t bad = ~nibble_mask(ok);
        if (bad) return p + (__builtin_ctzll(bad) >> 2);
        p += 16;
    }
    while (p < end && sc_is_addr(*p)) ++p;
    return p;
}

inline const char* ra_scan_token_end(const char* p, const char* end) {
    while (p + 16 <= end) {
        uint8x16_t v = vld1q_u8((const uint8_t*)p);
        uint8x16_t ws =
            vorrq_u8(vceqq_u8(v, vdupq_n_u8(' ')), in_range(v, 0x09, 4));
        uint64_t m = nibble_mask(ws);
        if (m) return p + (__builtin_ctzll(m) >> 2);
        p += 16;
    }
    while (p < end && !sc_is_sp(*p)) ++p;
    return p;
}

// Dotted-quad fast parse: same accept-only-when-provable contract as the
// AVX2 build, with byte-wise classification over the <=16-byte window.
inline int ra_scan_ipv4(const char** pp, const char* end, uint32_t* out) {
    const char* p = *pp;
    int64_t avail = end - p;
    if (avail < 7) return -1;
    int64_t n = avail < 16 ? avail : 16;
    int64_t t = 0;
    while (t < n && (sc_is_dig(p[t]) || p[t] == '.')) ++t;
    if (t == n && p + n < end) return -1;
    uint32_t value = 0;
    int dots = 0;
    int64_t pos = 0;
    for (int64_t i = 0; i <= t; ++i) {
        if (i == t || p[i] == '.') {
            int64_t len = i - pos;
            if (len < 1 || len > 3) return -1;
            uint32_t o = 0;
            for (int64_t j = pos; j < i; ++j) {
                if (!sc_is_dig(p[j])) return -1;
                o = o * 10 + (uint32_t)(p[j] - '0');
            }
            if (o > 255) return -1;
            value = (value << 8) | o;
            pos = i + 1;
            if (i < t) ++dots;
        }
    }
    if (dots != 3) return -1;
    *out = value;
    *pp = p + t;
    return 1;
}

}  // namespace

#define RA_PARSE_NS ra_neon
#include "asaparse_line.inl"
#undef RA_PARSE_NS

namespace ra_parse {
HandleLineFn neon_handle_line() { return &ra_neon::handle_line; }
}  // namespace ra_parse

#else  // !NEON

namespace ra_parse {
HandleLineFn neon_handle_line() { return nullptr; }
}  // namespace ra_parse

#endif
