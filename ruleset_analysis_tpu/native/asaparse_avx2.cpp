// AVX2 build of the ASA line parser (x86-64).
//
// The scan kernels below are file-local inline functions, so the
// compiler inlines them straight into the tokenizer loops of
// asaparse_line.inl — per-line dispatch, zero per-token call overhead
// (a ScanOps-style function pointer per token was measured at
// 0.93-0.95x).  Compiled with -mavx2 by the Makefile on x86-64; on
// other architectures this TU reduces to a nullptr stub.
//
// No load ever touches bytes past `end`: 32-byte blocks run strictly
// inside [p, end), tails fall back to the scalar character test, and
// the dotted-quad window is memcpy'd — the mutant sweep places lines
// flush against the end of exactly-sized buffers to enforce this.

#include "asaparse_types.h"

#if defined(__AVX2__)

#include <immintrin.h>

#include <cstring>

namespace {

inline bool sc_is_sp(char c) {
    return c == ' ' || c == '\t' || c == '\v' || c == '\f' || c == '\r' ||
           c == '\n';
}
inline bool sc_is_dig(char c) { return c >= '0' && c <= '9'; }
inline bool sc_is_addr(char c) {
    return sc_is_dig(c) || (c >= 'a' && c <= 'f') || (c >= 'A' && c <= 'F') ||
           c == ':' || c == '.';
}

// unsigned "x - lo <= span" range test per byte
inline __m256i in_range(__m256i v, char lo, int span) {
    __m256i d = _mm256_sub_epi8(v, _mm256_set1_epi8(lo));
    return _mm256_cmpeq_epi8(_mm256_min_epu8(d, _mm256_set1_epi8((char)span)),
                             d);
}

inline const char* ra_scan_addr_end(const char* p, const char* end) {
    while (p + 32 <= end) {
        __m256i v = _mm256_loadu_si256((const __m256i*)p);
        // '0'..':' is one contiguous range (0x30..0x3A): digits + colon
        __m256i ok = _mm256_or_si256(
            _mm256_or_si256(in_range(v, 0x30, 0x0A), in_range(v, 0x41, 5)),
            _mm256_or_si256(in_range(v, 0x61, 5),
                            _mm256_cmpeq_epi8(v, _mm256_set1_epi8('.'))));
        uint32_t bad = ~(uint32_t)_mm256_movemask_epi8(ok);
        if (bad) return p + __builtin_ctz(bad);
        p += 32;
    }
    while (p < end && sc_is_addr(*p)) ++p;
    return p;
}

inline const char* ra_scan_token_end(const char* p, const char* end) {
    while (p + 32 <= end) {
        __m256i v = _mm256_loadu_si256((const __m256i*)p);
        __m256i ws = _mm256_or_si256(
            _mm256_cmpeq_epi8(v, _mm256_set1_epi8(' ')), in_range(v, 0x09, 4));
        uint32_t m = (uint32_t)_mm256_movemask_epi8(ws);
        if (m) return p + __builtin_ctz(m);
        p += 32;
    }
    while (p < end && !sc_is_sp(*p)) ++p;
    return p;
}

// Dotted-quad fast parse: classify a <=16-byte window with SSE, derive
// octets from the dot mask.  Accepts ONLY patterns the scalar reference
// provably accepts with the same value (exactly 3 dots, octet lengths
// 1..3, values <= 255, run terminated inside the window or exactly at
// `end`); everything else defers (-1) to the scalar loop.
inline int ra_scan_ipv4(const char** pp, const char* end, uint32_t* out) {
    const char* p = *pp;
    int64_t avail = end - p;
    if (avail < 7) return -1;  // shortest quad "1.2.3.4"
    int64_t n = avail < 16 ? avail : 16;
    unsigned char buf[16];
    memset(buf, 0, sizeof(buf));
    memcpy(buf, p, (size_t)n);
    __m128i v = _mm_loadu_si128((const __m128i*)buf);
    __m128i d = _mm_sub_epi8(v, _mm_set1_epi8(0x30));
    __m128i isd = _mm_cmpeq_epi8(_mm_min_epu8(d, _mm_set1_epi8(9)), d);
    uint32_t lanes = (n == 16) ? 0xFFFFu : ((1u << n) - 1);
    uint32_t dm = (uint32_t)_mm_movemask_epi8(isd) & lanes;
    uint32_t dotm =
        (uint32_t)_mm_movemask_epi8(_mm_cmpeq_epi8(v, _mm_set1_epi8('.'))) &
        lanes;
    uint32_t run = dm | dotm;
    uint32_t nonrun = ~run & lanes;
    int64_t t = nonrun ? __builtin_ctz(nonrun) : n;
    if (t == n && p + n < end) return -1;  // run extends past the window
    uint32_t rm = t >= 16 ? 0xFFFFu : ((1u << t) - 1);
    dotm &= rm;
    if (__builtin_popcount(dotm) != 3) return -1;
    uint32_t value = 0;
    int64_t pos = 0;
    uint32_t dots = dotm;
    for (int oi = 0; oi < 4; ++oi) {
        int64_t oe = (oi < 3) ? __builtin_ctz(dots) : t;
        if (oi < 3) dots &= dots - 1;
        int64_t len = oe - pos;
        if (len < 1 || len > 3) return -1;  // leading-zero long octets: scalar
        uint32_t o = 0;
        for (int64_t i = pos; i < oe; ++i) {
            if (!(dm & (1u << i))) return -1;  // a dot where a digit must be
            o = o * 10 + (uint32_t)(buf[i] - '0');
        }
        if (o > 255) return -1;  // scalar rejects too; defer the verdict
        value = (value << 8) | o;
        pos = oe + 1;
    }
    // scalar trailing check already satisfied: byte t is neither a digit
    // nor '.', or the run ends exactly at `end`
    *out = value;
    *pp = p + t;
    return 1;
}

}  // namespace

#define RA_PARSE_NS ra_avx2
#include "asaparse_line.inl"
#undef RA_PARSE_NS

namespace ra_parse {
HandleLineFn avx2_handle_line() {
    return __builtin_cpu_supports("avx2") ? &ra_avx2::handle_line : nullptr;
}
}  // namespace ra_parse

#else  // !__AVX2__

namespace ra_parse {
HandleLineFn avx2_handle_line() { return nullptr; }
}  // namespace ra_parse

#endif
