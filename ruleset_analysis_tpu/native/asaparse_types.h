// Shared types of the ASA line parser (ISSUE 11 SIMD split).
//
// The line parser body (asaparse_line.inl) compiles once per ISA —
// scalar in asaparse.cpp, AVX2 in asaparse_avx2.cpp, NEON in
// asaparse_neon.cpp — and the chunk loops dispatch through a
// HandleLineFn pointer selected at runtime.  These types cross that
// boundary, so they live outside the per-ISA namespaces.

#pragma once

#include <cstdint>
#include <string>
#include <unordered_map>

namespace ra_parse {

struct Packer {
    // key: firewall + '\x01' + acl   -> acl gid  (named-ACL messages)
    //      firewall + '\x02' + iface -> acl gid  (in-direction binding)
    //      firewall + '\x03' + iface -> acl gid  (out-direction binding)
    std::unordered_map<std::string, uint32_t> resolve;
    int64_t parsed = 0;   // ACL evaluations emitted (LinePacker.parsed)
    int64_t skipped = 0;  // lines yielding none (LinePacker.skipped)
};

// Per-thread parse context: the shared resolve table is read-only during a
// parse; everything mutable is thread-local so N workers can parse one
// batch's line ranges concurrently (the Hadoop input-split analog,
// SURVEY.md §2 L2).
struct LocalCtx {
    const std::unordered_map<std::string, uint32_t>* resolve;
    std::string keybuf;
};

// Parse one line; emit its ACL evaluations into the column-major output.
// Same contract for every ISA build — see the documentation block on
// handle_line in asaparse_line.inl.
using HandleLineFn = int (*)(LocalCtx* pk, const char* ls, const char* le,
                             uint32_t* out, int64_t cap, int64_t row,
                             uint32_t* out6, int64_t cap6, int64_t* row6);

// Per-ISA entry points: return the TU's handle_line, or nullptr when the
// TU was compiled without the ISA or the CPU lacks it at runtime.
HandleLineFn scalar_handle_line();
HandleLineFn avx2_handle_line();
HandleLineFn neon_handle_line();

}  // namespace ra_parse
