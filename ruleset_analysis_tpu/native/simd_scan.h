// SIMD BULK scan primitives for the ASA tokenizer (ISSUE 11).
//
// This table holds the primitives whose inputs are megabytes, not
// tokens — newline indexing, counting, and skipping — where one
// function call amortizes over the whole buffer.  Per-TOKEN scans
// (token ends, address runs, the dotted-quad parse) do NOT go through a
// table: an indirect call per 10-byte token was measured at 0.93-0.95x,
// so those inline into the per-ISA line-parser builds instead
// (asaparse_line.inl included by asaparse_avx2.cpp / asaparse_neon.cpp).
//
// Contract: every primitive must return EXACTLY what the scalar loop it
// replaces would return, for every input, including truncated tails at
// buffer edges — implementations never read past [p, p+n).  The 12k
// mutant sweep in tests/test_fastparse.py asserts output identity of
// the full parse under both dispatch states.

#pragma once

#include <cstddef>
#include <cstdint>

namespace ra_simd {

struct ScanOps {
    const char* name;  // "avx2" | "neon" (artifact / test reporting)

    // Newline count over [p, p+n).
    int64_t (*count_nl)(const char* p, int64_t n);

    // Offsets (relative to p) of the first min(max_out, total) newlines
    // in [p, p+n), written to out; returns the count written.  Stops
    // scanning once max_out positions are found.
    int64_t (*nl_positions)(const char* p, int64_t n, uint32_t* out,
                            int64_t max_out);

    // Skip past up to k newlines: returns c = min(k, newlines in
    // [p, p+n)) and sets *bytes to the offset one past the c-th newline
    // (0 when c == 0).  The caller layers the trailing-fragment /
    // `final` semantics on top.
    int64_t (*nl_skip)(const char* p, int64_t n, int64_t k, int64_t* bytes);
};

// nullptr when the TU was compiled without the ISA or the CPU lacks it.
const ScanOps* avx2_ops();
const ScanOps* neon_ops();

}  // namespace ra_simd
