// AVX2 implementations of the tokenizer scan primitives (x86-64).
//
// Compiled with -mavx2 by the Makefile on x86-64 hosts only; on other
// architectures (or a toolchain without AVX2 support) the preprocessor
// guard below reduces this TU to a nullptr stub, so the link never
// breaks and the dispatch in asaparse.cpp simply stays scalar.
//
// Every loop processes 32-byte blocks strictly inside [p, end) and
// finishes the tail with the scalar character test — no load ever
// touches bytes past `end`, which is what lets the mutant sweep place
// lines flush against the end of an exactly-sized buffer.

#include "simd_scan.h"

#if defined(__AVX2__)

#include <immintrin.h>

#include <cstring>

namespace {

inline bool sc_is_sp(char c) {
    return c == ' ' || c == '\t' || c == '\v' || c == '\f' || c == '\r' ||
           c == '\n';
}
inline bool sc_is_dig(char c) { return c >= '0' && c <= '9'; }
inline bool sc_is_addr(char c) {
    return sc_is_dig(c) || (c >= 'a' && c <= 'f') || (c >= 'A' && c <= 'F') ||
           c == ':' || c == '.';
}

// unsigned "x - lo <= span" range test per byte: min_epu8(d, span) == d
inline __m256i in_range(__m256i v, char lo, int span) {
    __m256i d = _mm256_sub_epi8(v, _mm256_set1_epi8(lo));
    return _mm256_cmpeq_epi8(_mm256_min_epu8(d, _mm256_set1_epi8((char)span)), d);
}





int64_t count_nl_avx2(const char* p, int64_t n) {
    const char* end = p + n;
    const __m256i nl = _mm256_set1_epi8('\n');
    int64_t c = 0;
    while (p + 32 <= end) {
        __m256i v = _mm256_loadu_si256((const __m256i*)p);
        c += __builtin_popcount(
            (uint32_t)_mm256_movemask_epi8(_mm256_cmpeq_epi8(v, nl)));
        p += 32;
    }
    while (p < end) c += (*p++ == '\n');
    return c;
}

int64_t nl_positions_avx2(const char* p, int64_t n, uint32_t* out,
                          int64_t max_out) {
    const char* base = p;
    const char* end = p + n;
    const __m256i nl = _mm256_set1_epi8('\n');
    int64_t c = 0;
    while (p + 32 <= end && c < max_out) {
        __m256i v = _mm256_loadu_si256((const __m256i*)p);
        uint32_t m = (uint32_t)_mm256_movemask_epi8(_mm256_cmpeq_epi8(v, nl));
        while (m) {
            out[c++] = (uint32_t)(p - base) + (uint32_t)__builtin_ctz(m);
            if (c == max_out) return c;
            m &= m - 1;
        }
        p += 32;
    }
    while (p < end && c < max_out) {
        if (*p == '\n') out[c++] = (uint32_t)(p - base);
        ++p;
    }
    return c;
}

int64_t nl_skip_avx2(const char* p, int64_t n, int64_t k, int64_t* bytes) {
    const char* base = p;
    const char* end = p + n;
    const __m256i nl = _mm256_set1_epi8('\n');
    int64_t c = 0;
    int64_t past_last = 0;  // offset one past the last counted newline
    while (p + 32 <= end && c < k) {
        __m256i v = _mm256_loadu_si256((const __m256i*)p);
        uint32_t m = (uint32_t)_mm256_movemask_epi8(_mm256_cmpeq_epi8(v, nl));
        int cnt = __builtin_popcount(m);
        if (c + cnt < k) {
            if (cnt) {
                // highest set bit = last newline in this block
                past_last = (p - base) + (31 - __builtin_clz(m)) + 1;
            }
            c += cnt;
        } else {
            // the k-th newline is inside this block: walk its set bits
            while (c < k) {
                past_last = (p - base) + __builtin_ctz(m) + 1;
                m &= m - 1;
                ++c;
            }
        }
        p += 32;
    }
    while (p < end && c < k) {
        if (*p == '\n') {
            ++c;
            past_last = (p - base) + 1;
        }
        ++p;
    }
    *bytes = past_last;
    return c;
}



const ra_simd::ScanOps kOps = {
    "avx2", count_nl_avx2, nl_positions_avx2, nl_skip_avx2,
};

}  // namespace

namespace ra_simd {
const ScanOps* avx2_ops() {
    return __builtin_cpu_supports("avx2") ? &kOps : nullptr;
}
}  // namespace ra_simd

#else  // !__AVX2__

namespace ra_simd {
const ScanOps* avx2_ops() { return nullptr; }
}  // namespace ra_simd

#endif
