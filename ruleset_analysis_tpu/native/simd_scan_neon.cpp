// NEON implementations of the tokenizer scan primitives (aarch64).
//
// NEON is baseline on aarch64, so no runtime CPU probe is needed — the
// guard below is purely compile-time.  Mask extraction uses the
// vshrn_n_u16 narrowing trick (one 64-bit nibble mask per 16-byte
// block, 4 bits per lane).  The same no-read-past-end discipline as the
// AVX2 TU applies: 16-byte blocks strictly inside [p, end), scalar tail.

#include "simd_scan.h"

#if defined(__ARM_NEON) || defined(__aarch64__)

#include <arm_neon.h>

#include <cstring>

namespace {

inline bool sc_is_sp(char c) {
    return c == ' ' || c == '\t' || c == '\v' || c == '\f' || c == '\r' ||
           c == '\n';
}
inline bool sc_is_dig(char c) { return c >= '0' && c <= '9'; }
inline bool sc_is_addr(char c) {
    return sc_is_dig(c) || (c >= 'a' && c <= 'f') || (c >= 'A' && c <= 'F') ||
           c == ':' || c == '.';
}

// 4 bits per byte lane: bit i*4 set iff lane i's comparison was true
inline uint64_t nibble_mask(uint8x16_t eq) {
    return vget_lane_u64(
        vreinterpret_u64_u8(vshrn_n_u16(vreinterpretq_u16_u8(eq), 4)), 0);
}

inline uint8x16_t in_range(uint8x16_t v, uint8_t lo, uint8_t span) {
    return vcleq_u8(vsubq_u8(v, vdupq_n_u8(lo)), vdupq_n_u8(span));
}





int64_t count_nl_neon(const char* p, int64_t n) {
    const char* end = p + n;
    const uint8x16_t nl = vdupq_n_u8('\n');
    int64_t c = 0;
    while (p + 16 <= end) {
        uint8x16_t eq = vceqq_u8(vld1q_u8((const uint8_t*)p), nl);
        // each matching lane contributes 0xFF; sum/255 = count
        c += vaddvq_u8(vshrq_n_u8(eq, 7));
        p += 16;
    }
    while (p < end) c += (*p++ == '\n');
    return c;
}

int64_t nl_positions_neon(const char* p, int64_t n, uint32_t* out,
                          int64_t max_out) {
    const char* base = p;
    const char* end = p + n;
    const uint8x16_t nl = vdupq_n_u8('\n');
    int64_t c = 0;
    while (p + 16 <= end && c < max_out) {
        uint64_t m = nibble_mask(vceqq_u8(vld1q_u8((const uint8_t*)p), nl));
        // each matching lane owns one 4-bit nibble: consume nibble by
        // nibble (clear all 4 bits so ctz advances a full lane)
        while (m) {
            int lane = __builtin_ctzll(m) >> 2;
            out[c++] = (uint32_t)(p - base) + (uint32_t)lane;
            if (c == max_out) return c;
            m &= ~(0xFull << (4 * lane));
        }
        p += 16;
    }
    while (p < end && c < max_out) {
        if (*p == '\n') out[c++] = (uint32_t)(p - base);
        ++p;
    }
    return c;
}

int64_t nl_skip_neon(const char* p, int64_t n, int64_t k, int64_t* bytes) {
    const char* base = p;
    const char* end = p + n;
    const uint8x16_t nl = vdupq_n_u8('\n');
    int64_t c = 0;
    int64_t past_last = 0;
    while (p + 16 <= end && c < k) {
        uint8x16_t eq = vceqq_u8(vld1q_u8((const uint8_t*)p), nl);
        int cnt = vaddvq_u8(vshrq_n_u8(eq, 7));
        if (cnt && c + cnt < k) {
            uint64_t m = nibble_mask(eq);
            past_last = (p - base) + (63 - __builtin_clzll(m)) / 4 + 1;
            c += cnt;
        } else if (cnt) {
            for (int i = 0; i < 16 && c < k; ++i) {
                if (p[i] == '\n') {
                    ++c;
                    past_last = (p - base) + i + 1;
                }
            }
        }
        p += 16;
    }
    while (p < end && c < k) {
        if (*p == '\n') {
            ++c;
            past_last = (p - base) + 1;
        }
        ++p;
    }
    *bytes = past_last;
    return c;
}



const ra_simd::ScanOps kOps = {
    "neon", count_nl_neon, nl_positions_neon, nl_skip_neon,
};

}  // namespace

namespace ra_simd {
const ScanOps* neon_ops() { return &kOps; }
}  // namespace ra_simd

#else  // !NEON

namespace ra_simd {
const ScanOps* neon_ops() { return nullptr; }
}  // namespace ra_simd

#endif
