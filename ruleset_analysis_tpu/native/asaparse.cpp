// Native ASA syslog parser + tuple packer (the host-side hot loop).
//
// SURVEY.md §8.2 names host-side syslog parsing as the end-to-end
// bottleneck at target rates: the device pipeline sustains millions of
// lines/sec/chip, so a Python regex parser starves it.  This library is
// the native tier of the runtime: it parses raw ASA syslog bytes and
// packs valid lines directly into the column-major [TUPLE_COLS, B]
// uint32 batch layout the device step consumes — one pass, no Python
// objects, no regex engine.
//
// Semantics mirror ruleset_analysis_tpu/hostside/syslog.py (parse_line)
// and pack.py (LinePacker) exactly; tests/test_fastparse.py asserts the
// two paths produce identical batches on synthetic and edge-case
// corpora.  Both paths skip lines whose IPv4 octets, ports (> 65535) or
// protocol numbers (> 255) exceed their field widths.
//
// C ABI only (loaded via ctypes; no pybind11 in this image).

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t TUPLE_COLS = 7;

struct Packer {
    // key: firewall + '\x01' + acl   -> acl gid  (named-ACL messages)
    //      firewall + '\x02' + iface -> acl gid  (in-direction binding)
    //      firewall + '\x03' + iface -> acl gid  (out-direction binding)
    std::unordered_map<std::string, uint32_t> resolve;
    int64_t parsed = 0;   // ACL evaluations emitted (LinePacker.parsed)
    int64_t skipped = 0;  // lines yielding none (LinePacker.skipped)
};

// Per-thread parse context: the shared resolve table is read-only during a
// parse; everything mutable is thread-local so N workers can parse one
// batch's line ranges concurrently (the Hadoop input-split analog,
// SURVEY.md §2 L2).
struct LocalCtx {
    const std::unordered_map<std::string, uint32_t>* resolve;
    std::string keybuf;
};

inline bool is_sp(char c) { return c == ' ' || c == '\t' || c == '\v' || c == '\f' || c == '\r' || c == '\n'; }
inline bool is_dig(char c) { return c >= '0' && c <= '9'; }

const char* find_sub(const char* p, const char* end, const char* pat, size_t n) {
    if (end - p < (std::ptrdiff_t)n) return nullptr;
    return (const char*)memmem(p, end - p, pat, n);
}

// Parse a decimal run; false if no digits or value > 2^32-1.
bool parse_u32(const char*& p, const char* end, uint32_t* out) {
    if (p >= end || !is_dig(*p)) return false;
    uint64_t v = 0;
    const char* q = p;
    while (q < end && is_dig(*q)) {
        v = v * 10 + (uint64_t)(*q - '0');
        if (v > 0xFFFFFFFFull) return false;
        ++q;
    }
    *out = (uint32_t)v;
    p = q;
    return true;
}

inline bool is_hex(char c) {
    return is_dig(c) || (c >= 'a' && c <= 'f') || (c >= 'A' && c <= 'F');
}
inline bool is_addr_char(char c) { return is_hex(c) || c == ':' || c == '.'; }
inline uint32_t hex_val(char c) {
    if (is_dig(c)) return (uint32_t)(c - '0');
    if (c >= 'a' && c <= 'f') return (uint32_t)(c - 'a' + 10);
    return (uint32_t)(c - 'A' + 10);
}

// Dotted-quad IPv4 over a [0-9.] run: exactly 4 octets, each 0..255
// (hostside.aclparse.ip_to_u32 semantics).  Advances p past the run on
// success; on failure leaves p unspecified and returns false.
bool parse_ipv4_run(const char*& p, const char* end, uint32_t* out) {
    uint32_t v = 0;
    int octets = 0;
    const char* q = p;
    while (octets < 4) {
        if (q >= end || !is_dig(*q)) return false;
        uint64_t o = 0;
        while (q < end && is_dig(*q)) {
            o = o * 10 + (uint64_t)(*q - '0');
            if (o > 0xFFFFFFFFull) return false;
            ++q;
        }
        if (o > 255) return false;
        v = (v << 8) | (uint32_t)o;
        ++octets;
        if (octets < 4) {
            if (q >= end || *q != '.') return false;
            ++q;
        }
    }
    // the regex run [\d.]+ is maximal: a trailing '.' or digit means the
    // run does not parse as exactly four octets
    if (q < end && (*q == '.' || is_dig(*q))) return false;
    *out = v;
    p = q;
    return true;
}

// One parsed address of either family: fam is 4 or 6; v6 addresses carry
// 4 big-endian uint32 limbs (pack.u128_limbs layout).
struct Addr {
    uint32_t fam = 4;
    uint32_t v4 = 0;
    uint32_t l[4] = {0, 0, 0, 0};
};

// Parse [rs, re) — one complete address text run — as an IPv6 literal
// (RFC 4291 forms: hex groups, one '::' compression, optional embedded
// trailing dotted quad).  Mirrors the stdlib ipaddress acceptance the
// Python path delegates to (hostside.aclparse.ip6_to_int): groups are
// 1-4 hex digits, exactly 8 groups without '::', fewer with, the
// embedded v4 counts as two groups and may only appear last.
bool parse_ipv6_text(const char* rs, const char* re, uint32_t limbs[4]) {
    uint16_t head[8];
    uint16_t tail[8];
    int n_head = 0, n_tail = 0;
    bool compressed = false;
    const char* p = rs;
    if (p >= re) return false;
    if (*p == ':') {
        // must be a leading '::'
        if (p + 1 >= re || p[1] != ':') return false;
        compressed = true;
        p += 2;
    }
    bool want_group = !(compressed && p == re);
    while (p < re) {
        // embedded trailing dotted quad? detect a digit run followed by '.'
        const char* q = p;
        while (q < re && is_dig(*q)) ++q;
        if (q > p && q < re && *q == '.') {
            const char* v4p = p;
            uint32_t v4;
            if (!parse_ipv4_run(v4p, re, &v4) || v4p != re) return false;
            uint16_t* dst = compressed ? tail : head;
            int& n = compressed ? n_tail : n_head;
            if (n + 2 > 8) return false;
            dst[n++] = (uint16_t)(v4 >> 16);
            dst[n++] = (uint16_t)(v4 & 0xFFFF);
            p = re;
            want_group = false;
            break;
        }
        // hex group: 1-4 hex digits
        uint32_t g = 0;
        int nd = 0;
        while (p < re && is_hex(*p) && nd < 5) {
            g = (g << 4) | hex_val(*p);
            ++p;
            ++nd;
        }
        if (nd == 0 || nd > 4) return false;
        uint16_t* dst = compressed ? tail : head;
        int& n = compressed ? n_tail : n_head;
        if (n >= 8) return false;
        dst[n++] = (uint16_t)g;
        want_group = false;
        if (p < re) {
            if (*p != ':') return false;
            ++p;
            if (p < re && *p == ':') {
                if (compressed) return false;  // second '::'
                compressed = true;
                ++p;
                if (p == re) { want_group = false; break; }
            } else {
                if (p == re) return false;  // single trailing ':'
                want_group = true;
            }
        }
    }
    if (want_group) return false;
    int total = n_head + n_tail;
    if (compressed ? total >= 8 : total != 8) return false;
    uint16_t groups[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    for (int i = 0; i < n_head; ++i) groups[i] = head[i];
    for (int i = 0; i < n_tail; ++i) groups[8 - n_tail + i] = tail[i];
    for (int i = 0; i < 4; ++i)
        limbs[i] = ((uint32_t)groups[2 * i] << 16) | groups[2 * i + 1];
    return true;
}

// Parse the maximal [0-9A-Fa-f:.] run at p as an address of either
// family (the Python regexes capture exactly this class and then parse
// by ':' presence).  Returns 1 on success (p past the run), 0 when the
// run is not address-shaped at all (structural failure — caller keeps
// scanning), -1 when the run IS the address capture but its value is
// invalid (semantic failure: Python raises inside _addr and the whole
// line skips with no rescan).
int parse_addr_run(const char*& p, const char* end, Addr* a) {
    const char* rs = p;
    const char* re = rs;
    bool has_colon = false;
    while (re < end && is_addr_char(*re)) {
        has_colon |= (*re == ':');
        ++re;
    }
    if (re == rs) return 0;
    if (!has_colon) {
        const char* q = rs;
        uint32_t v4;
        if (!parse_ipv4_run(q, re, &v4) || q != re) return -1;
        a->fam = 4;
        a->v4 = v4;
        p = re;
        return 1;
    }
    if (!parse_ipv6_text(rs, re, a->l)) return -1;
    a->fam = 6;
    p = re;
    return 1;
}

void skip_ws(const char*& p, const char* end) {
    while (p < end && is_sp(*p)) ++p;
}

bool skip_ws1(const char*& p, const char* end) {  // require at least one
    if (p >= end || !is_sp(*p)) return false;
    skip_ws(p, end);
    return true;
}

// Token = maximal non-space run.
bool token(const char*& p, const char* end, const char** t0, const char** t1) {
    if (p >= end || is_sp(*p)) return false;
    *t0 = p;
    while (p < end && !is_sp(*p)) ++p;
    *t1 = p;
    return true;
}

bool tok_eq(const char* t0, const char* t1, const char* s) {
    size_t n = strlen(s);
    return (size_t)(t1 - t0) == n && memcmp(t0, s, n) == 0;
}

// _proto_num: PROTO_NUMBERS name (case-insensitive) -> number; else
// decimal; else 0.
uint32_t proto_num(const char* t0, const char* t1) {
    char buf[16];
    size_t n = (size_t)(t1 - t0);
    if (n < sizeof(buf)) {
        for (size_t i = 0; i < n; ++i) {
            char c = t0[i];
            buf[i] = (c >= 'A' && c <= 'Z') ? (char)(c + 32) : c;
        }
        buf[n] = 0;
        // ordered by real-traffic frequency: tcp/udp dominate ASA logs
        struct { const char* name; uint32_t v; } static const tbl[] = {
            {"tcp", 6},  {"udp", 17},  {"icmp", 1},  {"ip", 0},
            {"igmp", 2}, {"ipinip", 4}, {"gre", 47},  {"esp", 50},
            {"ah", 51},  {"icmp6", 58}, {"eigrp", 88}, {"ospf", 89},
            {"nos", 94}, {"pim", 103}, {"pcp", 108}, {"snp", 109},
            {"sctp", 132},
        };
        for (auto& e : tbl)
            if (strcmp(buf, e.name) == 0) return e.v;
    }
    const char* p = t0;
    uint32_t v = 0;
    if (parse_u32(p, t1, &v) && p == t1) return v;
    return 0;
}

struct Parsed {
    const char* fw0; const char* fw1;
    const char* acl0; const char* acl1;   // acl0 == nullptr: resolve by iface
    const char* if0; const char* if1;     // ingress interface (in binding)
    const char* eif0 = nullptr;           // egress interface (out binding);
    const char* eif1 = nullptr;           // 302013/302015 only
    uint32_t proto, sport, dport;
    Addr src, dst;                        // either family; must agree
};

// "if/ADDR(port)" endpoint of 106100: iface is the shortest prefix whose
// '/' is followed by a parseable "ADDR(port)" of either family.
// Returns 1 ok / 0 structural mismatch (caller keeps scanning) /
// -1 semantic failure (address text captured but invalid — Python raises
// inside _addr and the whole line skips, so callers must abort).
int endpoint_slash_paren(const char*& p, const char* end,
                         const char** if0, const char** if1,
                         Addr* addr, uint32_t* port) {
    const char* t0; const char* t1;
    const char* q = p;
    if (!token(q, end, &t0, &t1)) return 0;
    for (const char* s = t0; s < t1; ++s) {
        if (*s != '/') continue;
        if (s == t0) continue;  // iface must be non-empty
        const char* c = s + 1;
        // structure first: maximal addr run, then '(digits)'
        const char* re = c;
        while (re < t1 && is_addr_char(*re)) ++re;
        if (re == c || re >= t1 || *re != '(') continue;
        const char* pc = re + 1;
        uint32_t pv;
        if (!parse_u32(pc, t1, &pv)) continue;
        if (pc >= t1 || *pc != ')') continue;
        ++pc;
        Addr a;
        const char* ac = c;
        if (parse_addr_run(ac, re, &a) != 1 || ac != re) return -1;
        *if0 = t0; *if1 = s; *addr = a; *port = pv;
        p = pc;  // just past ')': an extra paren group may follow unspaced
        return 1;
    }
    return 0;
}

// "if:ADDR[/port]" endpoint of 106023 (port optional, defaults 0) and
// 302013 (port required).  Same 1/0/-1 contract as endpoint_slash_paren.
//
// ``require_token_end``: the 106023 SRC endpoint is followed by ``\s+dst``
// in the regex, so Python only commits to a colon split whose endpoint
// reaches the end of the token — a mid-token leftover is a STRUCTURAL
// mismatch that backtracks to a later colon (fuzz: "inside:1side:A.B.C.D"
// must split at the SECOND colon).  The DST endpoint is followed by
// ``.*?by`` (anything matches), so it commits to the first structural
// split and a bad value there skips the line — require_token_end=false.
int endpoint_colon(const char*& p, const char* end, bool port_required,
                   const char** if0, const char** if1,
                   Addr* addr, uint32_t* port,
                   bool require_token_end = false) {
    const char* t0; const char* t1;
    const char* q = p;
    if (!token(q, end, &t0, &t1)) return 0;
    for (const char* s = t0; s < t1; ++s) {
        if (*s != ':') continue;
        if (s == t0) continue;
        const char* c = s + 1;
        const char* re = c;
        while (re < t1 && is_addr_char(*re)) ++re;
        if (re == c) continue;
        uint32_t pv = 0;
        const char* after = re;
        if (after < t1 && *after == '/') {
            const char* c2 = after + 1;
            if (parse_u32(c2, t1, &pv)) after = c2;
            else if (port_required) continue;
        } else if (port_required) {
            continue;
        }
        if (require_token_end && after != t1) continue;
        Addr a;
        const char* ac = c;
        if (parse_addr_run(ac, re, &a) != 1 || ac != re) return -1;
        *if0 = t0; *if1 = s; *addr = a; *port = pv;
        p = after;
        return 1;
    }
    return 0;
}

bool parse_106100(const char* b, const char* be, Parsed* out) {
    const char* pos = b;
    while (true) {
        const char* hit = find_sub(pos, be, "access-list", 11);
        if (!hit) return false;
        pos = hit + 1;
        const char* p = hit + 11;
        const char* a0; const char* a1; const char* v0; const char* v1;
        const char* pr0; const char* pr1;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &a0, &a1)) continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &v0, &v1)) continue;
        if (!(tok_eq(v0, v1, "permitted") || tok_eq(v0, v1, "denied") ||
              tok_eq(v0, v1, "est-allowed")))
            continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &pr0, &pr1)) continue;
        if (!skip_ws1(p, be)) continue;
        const char* i0; const char* i1; Addr sa; uint32_t spo;
        int rc = endpoint_slash_paren(p, be, &i0, &i1, &sa, &spo);
        if (rc < 0) return false;  // invalid address text: line skips
        if (!rc) continue;
        if (p < be && *p == '(') {  // optional "(...)" (e.g. identity info)
            const char* c = (const char*)memchr(p, ')', be - p);
            if (c) p = c + 1;
        }
        skip_ws(p, be);
        if (p + 1 >= be || p[0] != '-' || p[1] != '>') continue;
        p += 2;
        skip_ws(p, be);
        const char* j0; const char* j1; Addr da; uint32_t dpo;
        rc = endpoint_slash_paren(p, be, &j0, &j1, &da, &dpo);
        if (rc < 0) return false;
        if (!rc) continue;
        if (sa.fam != da.fam) return false;  // mixed-family line: skip
        uint32_t proto = proto_num(pr0, pr1);
        // ICMP/ICMPv6: parenthesised values are type/code; type -> dport,
        // sport=0 (58 added with the v6 data model; mirrors syslog.py)
        if (proto == 1 || proto == 58) { dpo = spo; spo = 0; }
        out->acl0 = a0; out->acl1 = a1;
        out->if0 = i0; out->if1 = i1;
        out->proto = proto; out->src = sa; out->sport = spo;
        out->dst = da; out->dport = dpo;
        return true;
    }
}

bool parse_106023(const char* b, const char* be, Parsed* out) {
    const char* pos = b;
    while (true) {
        const char* hit = find_sub(pos, be, "Deny", 4);
        if (!hit) return false;
        pos = hit + 1;
        const char* p = hit + 4;
        const char* pr0; const char* pr1; const char* s0; const char* s1;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &pr0, &pr1)) continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &s0, &s1) || !tok_eq(s0, s1, "src")) continue;
        if (!skip_ws1(p, be)) continue;
        const char* i0; const char* i1; Addr sa; uint32_t spo;
        int rc = endpoint_colon(p, be, false, &i0, &i1, &sa, &spo,
                                /*require_token_end=*/true);
        if (rc < 0) return false;
        if (!rc) continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &s0, &s1) || !tok_eq(s0, s1, "dst")) continue;
        if (!skip_ws1(p, be)) continue;
        const char* j0; const char* j1; Addr da; uint32_t dpo;
        rc = endpoint_colon(p, be, false, &j0, &j1, &da, &dpo);
        if (rc < 0) return false;
        if (!rc) continue;
        if (sa.fam != da.fam) return false;
        // optional " (type T, code C)"
        bool have_type = false;
        uint32_t icmp_type = 0, tmp;
        {
            const char* q = p;
            if (skip_ws1(q, be) && q + 5 <= be && memcmp(q, "(type", 5) == 0) {
                const char* c = q + 5;
                if (skip_ws1(c, be) && parse_u32(c, be, &icmp_type) &&
                    c < be && *c == ',') {
                    ++c;
                    skip_ws(c, be);
                    if (c + 4 <= be && memcmp(c, "code", 4) == 0) {
                        c += 4;
                        if (skip_ws1(c, be) && parse_u32(c, be, &tmp) &&
                            c < be && *c == ')') {
                            have_type = true;
                            p = c + 1;
                        }
                    }
                }
            }
        }
        // .*?by\s+access-group\s+"<acl>"
        const char* scan = p;
        const char* a0 = nullptr; const char* a1 = nullptr;
        while (true) {
            const char* ag = find_sub(scan, be, "access-group", 12);
            if (!ag) break;
            scan = ag + 1;
            const char* back = ag;
            if (back <= p || !is_sp(back[-1])) continue;
            while (back > p && is_sp(back[-1])) --back;
            if (back - p < 2 || back[-1] != 'y' || back[-2] != 'b') continue;
            const char* c = ag + 12;
            if (!skip_ws1(c, be)) continue;
            if (c >= be || *c != '"') continue;
            ++c;
            const char* close = (const char*)memchr(c, '"', be - c);
            if (!close || close == c) continue;  // regex [^"]+ needs >=1 char
            a0 = c; a1 = close;
            break;
        }
        if (!a0) continue;
        uint32_t proto = proto_num(pr0, pr1);
        if ((proto == 1 || proto == 58) && have_type) { dpo = icmp_type; spo = 0; }
        out->acl0 = a0; out->acl1 = a1;
        out->if0 = i0; out->if1 = i1;
        out->proto = proto; out->src = sa; out->sport = spo;
        out->dst = da; out->dport = dpo;
        return true;
    }
}

bool parse_302013(const char* b, const char* be, Parsed* out) {
    const char* pos = b;
    while (true) {
        const char* hit = find_sub(pos, be, "Built", 5);
        if (!hit) return false;
        pos = hit + 1;
        const char* p = hit + 5;
        const char* t0; const char* t1;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &t0, &t1)) continue;
        bool inbound;
        if (tok_eq(t0, t1, "inbound")) inbound = true;
        else if (tok_eq(t0, t1, "outbound")) inbound = false;
        else continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &t0, &t1)) continue;
        uint32_t proto;
        if (tok_eq(t0, t1, "TCP")) proto = 6;
        else if (tok_eq(t0, t1, "UDP")) proto = 17;
        else continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "connection")) continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &t0, &t1)) continue;  // connection id
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "for")) continue;
        if (!skip_ws1(p, be)) continue;
        const char* ia0; const char* ia1; Addr aa; uint32_t poa;
        int rc = endpoint_colon(p, be, true, &ia0, &ia1, &aa, &poa);
        if (rc < 0) return false;
        if (!rc) continue;
        skip_ws(p, be);
        if (p < be && *p == '(') {
            const char* c = (const char*)memchr(p, ')', be - p);
            if (c) p = c + 1;
        }
        skip_ws(p, be);
        if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "to")) continue;
        if (!skip_ws1(p, be)) continue;
        const char* ib0; const char* ib1; Addr ab; uint32_t pob;
        rc = endpoint_colon(p, be, true, &ib0, &ib1, &ab, &pob);
        if (rc < 0) return false;
        if (!rc) continue;
        if (aa.fam != ab.fam) return false;
        out->acl0 = nullptr; out->acl1 = nullptr;
        // inbound: initiated at A (src=A, ingress=ifA, egress=ifB);
        // outbound: initiated at B (src=B, ingress=ifB, egress=ifA).
        // The egress side's out-direction ACL (if bound) also filters.
        if (inbound) {
            out->if0 = ia0; out->if1 = ia1;
            out->eif0 = ib0; out->eif1 = ib1;
            out->src = aa; out->sport = poa; out->dst = ab; out->dport = pob;
        } else {
            out->if0 = ib0; out->if1 = ib1;
            out->eif0 = ia0; out->eif1 = ia1;
            out->src = ab; out->sport = pob; out->dst = aa; out->dport = poa;
        }
        out->proto = proto;
        return true;
    }
}

// "ADDR/port" endpoint of the 106001/106006/106015 family ("from A/p to
// B/q"): a bare address of either family, '/', decimal port — no
// interface prefix.  Same 1/0/-1 contract as the other endpoints.
int endpoint_bare(const char*& p, const char* end, Addr* addr, uint32_t* port) {
    const char* re = p;
    while (re < end && is_addr_char(*re)) ++re;
    if (re == p) return 0;
    if (re >= end || *re != '/') return 0;
    const char* q = re + 1;
    uint32_t pv;
    if (!parse_u32(q, end, &pv)) return 0;
    Addr a;
    const char* ac = p;
    if (parse_addr_run(ac, re, &a) != 1 || ac != re) return -1;
    *addr = a; *port = pv;
    p = q;
    return 1;
}

// First "on interface <if>" at or after p (the 106001/106015 regexes use
// a lazy ".*?", so the FIRST occurrence wins, matching syslog.py).
bool on_interface_scan(const char* p, const char* be, const char** i0, const char** i1) {
    const char* scan = p;
    while (true) {
        const char* hit = find_sub(scan, be, "on", 2);
        if (!hit) return false;
        scan = hit + 1;
        // \bon: previous char must not be a word char (regex \b semantics)
        char prev = hit > p ? hit[-1] : ' ';
        if ((prev >= 'a' && prev <= 'z') || (prev >= 'A' && prev <= 'Z') ||
            (prev >= '0' && prev <= '9') || prev == '_')
            continue;
        const char* c = hit + 2;
        if (!skip_ws1(c, be)) continue;
        const char* t0; const char* t1;
        if (!token(c, be, &t0, &t1) || !tok_eq(t0, t1, "interface")) continue;
        if (!skip_ws1(c, be)) continue;
        if (!token(c, be, &t0, &t1)) continue;
        *i0 = t0; *i1 = t1;
        return true;
    }
}

// 106001: Inbound TCP connection denied from A/p to B/q flags ... on
// interface IF.  106015: Deny TCP (no connection) from A/p to B/q flags
// ... on interface IF.  106006: Deny inbound UDP from A/p to B/q on
// interface IF (immediately — no flags text).  All resolve via the
// interface's in-direction binding.  ``lead`` is a token sequence matched
// with \s+ separators (the regexes' flexibility); a token prefixed with
// '\x01' must instead be separated from its predecessor by EXACTLY one
// space (the 106015 pattern embeds a literal space inside
// "\(no connection\)").
bool parse_106001_like(const char* b, const char* be,
                       const char* const* lead, int lead_n,
                       bool need_flags, uint32_t proto, Parsed* out) {
    size_t first_n = strlen(lead[0]);
    const char* pos = b;
    while (true) {
        const char* hit = find_sub(pos, be, lead[0], first_n);
        if (!hit) return false;
        pos = hit + 1;
        const char* p = hit;
        const char* t0; const char* t1;
        bool lead_ok = true;
        for (int i = 0; i < lead_n; ++i) {
            const char* want = lead[i];
            if (i) {
                if (want[0] == '\x01') {
                    ++want;
                    if (p >= be || *p != ' ') { lead_ok = false; break; }
                    ++p;  // exactly one space; token() rejects a second
                } else if (!skip_ws1(p, be)) {
                    lead_ok = false;
                    break;
                }
            }
            if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, want)) {
                lead_ok = false;
                break;
            }
        }
        if (!lead_ok) continue;
        if (!skip_ws1(p, be)) continue;
        Addr sa; uint32_t spo;
        int rc = endpoint_bare(p, be, &sa, &spo);
        if (rc < 0) return false;
        if (!rc) continue;
        if (!skip_ws1(p, be)) continue;
        if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "to")) continue;
        if (!skip_ws1(p, be)) continue;
        Addr da; uint32_t dpo;
        rc = endpoint_bare(p, be, &da, &dpo);
        if (rc < 0) return false;
        if (!rc) continue;
        if (sa.fam != da.fam) return false;
        const char* i0; const char* i1;
        if (need_flags) {
            if (!skip_ws1(p, be)) continue;
            if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "flags")) continue;
            if (!on_interface_scan(p, be, &i0, &i1)) continue;
        } else {
            // 106006: "on interface" must follow the endpoints directly
            if (!skip_ws1(p, be)) continue;
            if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "on")) continue;
            if (!skip_ws1(p, be)) continue;
            if (!token(p, be, &t0, &t1) || !tok_eq(t0, t1, "interface")) continue;
            if (!skip_ws1(p, be)) continue;
            if (!token(p, be, &i0, &i1)) continue;
        }
        out->acl0 = nullptr; out->acl1 = nullptr;
        out->if0 = i0; out->if1 = i1;
        out->proto = proto;
        out->src = sa; out->sport = spo; out->dst = da; out->dport = dpo;
        return true;
    }
}

// Parse one line; emit its ACL evaluations into the column-major output.
//
// Returns the number of tuple rows written (0 = line skipped), or -1 when
// the line's rows do NOT fit in [row, cap) — the caller must close the
// batch without consuming the line.  A connection message whose ingress
// interface has an in-ACL and whose egress interface has an out-ACL emits
// TWO rows (two independent evaluations), mirroring LinePacker.
//
// Parity note (syslog.parse_line): _TAG_RE.search finds the FIRST
// well-formed "%ASA-<d>-<dddddd>:" marker that has a host token before
// it; the line's fate is then decided by that one tag — an unhandled
// msgid or a failed body parse means the line is skipped, with no retry
// against later markers.  Only malformed markers keep the scan going.
int handle_line(LocalCtx* pk, const char* ls, const char* le,
                uint32_t* out, int64_t cap, int64_t row,
                uint32_t* out6 = nullptr, int64_t cap6 = 0,
                int64_t* row6 = nullptr) {
    const char* pos = ls;
    const char* msgid = nullptr;
    const char* body = nullptr;
    const char* h0 = nullptr; const char* h1 = nullptr;
    while (true) {
        const char* tag = find_sub(pos, le, "%ASA-", 5);
        if (!tag) return 0;
        pos = tag + 1;
        const char* t = tag + 5;
        if (t >= le || !is_dig(*t)) continue;
        ++t;
        if (t >= le || *t != '-') continue;
        ++t;
        const char* mid = t;
        int nd = 0;
        while (t < le && is_dig(*t) && nd < 7) { ++t; ++nd; }
        if (nd != 6 || t >= le || *t != ':') continue;

        // host: last token (one optional trailing ':') before the marker
        const char* q = tag;
        while (q > ls && is_sp(q[-1])) --q;
        if (q > ls && q[-1] == ':') {
            --q;
            while (q > ls && is_sp(q[-1])) --q;
        }
        const char* he = q;
        while (q > ls && !is_sp(q[-1])) --q;
        if (he == q) continue;  // no host token; try a later marker

        msgid = mid;
        body = t + 1;
        skip_ws(body, le);
        h0 = q; h1 = he;
        break;
    }

    Parsed pr;
    bool ok;
    if (memcmp(msgid, "106100", 6) == 0) ok = parse_106100(body, le, &pr);
    else if (memcmp(msgid, "106023", 6) == 0) ok = parse_106023(body, le, &pr);
    else if (memcmp(msgid, "302013", 6) == 0 || memcmp(msgid, "302015", 6) == 0)
        ok = parse_302013(body, le, &pr);
    else if (memcmp(msgid, "106001", 6) == 0) {
        static const char* const lead[] = {
            "Inbound", "TCP", "connection", "denied", "from"};
        ok = parse_106001_like(body, le, lead, 5, /*need_flags=*/true, 6, &pr);
    } else if (memcmp(msgid, "106015", 6) == 0) {
        static const char* const lead[] = {
            // "\001" (octal): "\x01c..." would munch the 'c' as a hex digit
            "Deny", "TCP", "(no", "\001connection)", "from"};
        ok = parse_106001_like(body, le, lead, 5, /*need_flags=*/true, 6, &pr);
    } else if (memcmp(msgid, "106006", 6) == 0) {
        static const char* const lead[] = {"Deny", "inbound", "UDP", "from"};
        ok = parse_106001_like(body, le, lead, 4, /*need_flags=*/false, 17, &pr);
    } else return 0;  // unhandled message class
    if (!ok) return 0;
    // wire-width validation (syslog.py _field_ranges_ok): ports are
    // 16-bit, protocol numbers 8-bit; a line claiming more is malformed
    // and skipping beats silently truncating it into a false match
    if (pr.sport > 0xFFFF || pr.dport > 0xFFFF || pr.proto > 0xFF) return 0;

    // resolve into up to two gids: named ACL, or in-binding of the
    // ingress interface plus out-binding of the egress interface
    std::string& k = pk->keybuf;
    uint32_t gids[2];
    int n_gids = 0;
    if (pr.acl0) {
        k.assign(h0, h1 - h0);
        k.push_back('\x01');
        k.append(pr.acl0, pr.acl1 - pr.acl0);
        auto it = pk->resolve->find(k);
        if (it != pk->resolve->end()) gids[n_gids++] = it->second;
    } else {
        k.assign(h0, h1 - h0);
        k.push_back('\x02');
        k.append(pr.if0, pr.if1 - pr.if0);
        auto it = pk->resolve->find(k);
        if (it != pk->resolve->end()) gids[n_gids++] = it->second;
        if (pr.eif0) {
            k.assign(h0, h1 - h0);
            k.push_back('\x03');
            k.append(pr.eif0, pr.eif1 - pr.eif0);
            it = pk->resolve->find(k);
            if (it != pk->resolve->end()) gids[n_gids++] = it->second;
        }
    }
    if (n_gids == 0) return 0;
    if (pr.src.fam == 6) {
        // v6 line: rows land in the [TUPLE6_COLS=13, cap6] side plane
        // (mirrors LinePacker.pack_parsed2 / _TextSource staging); a v6
        // line against a pure-v4 ruleset is a counted skip
        if (!out6 || !row6) return 0;
        int64_t r6 = *row6;
        if (r6 + n_gids > cap6) return -1;
        for (int g = 0; g < n_gids; ++g, ++r6) {
            out6[0 * cap6 + r6] = gids[g];
            out6[1 * cap6 + r6] = pr.proto;
            for (int i = 0; i < 4; ++i) out6[(2 + i) * cap6 + r6] = pr.src.l[i];
            out6[6 * cap6 + r6] = pr.sport;
            for (int i = 0; i < 4; ++i) out6[(7 + i) * cap6 + r6] = pr.dst.l[i];
            out6[11 * cap6 + r6] = pr.dport;
            out6[12 * cap6 + r6] = 1;
        }
        *row6 = r6;
        return n_gids;
    }
    if (row + n_gids > cap) return -1;  // close the batch; line unconsumed
    for (int g = 0; g < n_gids; ++g, ++row) {
        out[0 * cap + row] = gids[g];
        out[1 * cap + row] = pr.proto;
        out[2 * cap + row] = pr.src.v4;
        out[3 * cap + row] = pr.sport;
        out[4 * cap + row] = pr.dst.v4;
        out[5 * cap + row] = pr.dport;
        out[6 * cap + row] = 1;
    }
    return n_gids;
}

}  // namespace

extern "C" {

void* asa_packer_new() { return new Packer(); }

void asa_packer_free(void* h) { delete (Packer*)h; }

void asa_packer_add_acl(void* h, const char* fw, const char* acl, uint32_t gid) {
    Packer* pk = (Packer*)h;
    std::string k(fw);
    k.push_back('\x01');
    k += acl;
    pk->resolve[k] = gid;
}

void asa_packer_add_binding(void* h, const char* fw, const char* iface, uint32_t gid) {
    Packer* pk = (Packer*)h;
    std::string k(fw);
    k.push_back('\x02');
    k += iface;
    pk->resolve[k] = gid;
}

// out-direction access-group: (firewall, egress interface) -> acl gid.
void asa_packer_add_binding_out(void* h, const char* fw, const char* iface, uint32_t gid) {
    Packer* pk = (Packer*)h;
    std::string k(fw);
    k.push_back('\x03');
    k += iface;
    pk->resolve[k] = gid;
}

int64_t asa_packer_parsed(void* h) { return ((Packer*)h)->parsed; }
int64_t asa_packer_skipped(void* h) { return ((Packer*)h)->skipped; }
void asa_packer_set_counts(void* h, int64_t parsed, int64_t skipped) {
    ((Packer*)h)->parsed = parsed;
    ((Packer*)h)->skipped = skipped;
}

// Zero the padding rows [valid, cap) of every column.  Callers allocate
// the output uninitialized (np.empty); the contract is "padding rows are
// all-zero", matching the pure-Python LinePacker exactly while memsetting
// only the (usually small) tail instead of the whole 28 MB buffer.
void zero_tail(uint32_t* out, int64_t cap, int64_t valid) {
    for (int64_t c = 0; c < TUPLE_COLS; ++c)
        memset(out + c * cap + valid, 0, (size_t)(cap - valid) * sizeof(uint32_t));
}

// Parse up to max_lines newline-terminated lines from buf[0:len) into the
// column-major uint32 out[TUPLE_COLS][cap], using up to n_threads parse
// workers over contiguous line ranges.  With final==0 a trailing fragment
// without '\n' is left unconsumed; with final!=0 it is parsed as the last
// line.  Returns bytes consumed; *n_lines_out lines were consumed,
// *n_valid_out tuples written (rows 0..n_valid-1; rows beyond are zero).
//
// Parallel structure (SURVEY.md §2 L2 — the input-split analog): one
// memchr pass builds the line-offset index; lines split evenly across
// workers; each worker parses its range into a private column-major slab
// with a thread-local context; a sequential compaction then concatenates
// the slabs' valid rows in range order.  The output — tuple order, counts,
// consumed bytes — is bit-identical to the single-threaded parse.
int64_t asa_pack_chunk_mt(void* h, const char* buf, int64_t len, int final_,
                          int64_t max_lines, uint32_t* out, int64_t cap,
                          int64_t* n_lines_out, int64_t* n_valid_out,
                          int n_threads) {
    Packer* pk = (Packer*)h;
    const char* end = buf + len;
    int64_t want = max_lines < cap ? max_lines : cap;

    // the parallel path indexes lines with uint32 offsets, and its
    // even-line split can't honor the "keep consuming raw lines while
    // valid < cap" contract that binds when max_lines > cap — route both
    // cases through the exact sequential loop
    if (n_threads != 1 && (len > (int64_t)0xFFFFFFFF || max_lines > cap))
        n_threads = 1;

    if (n_threads == 1) {
        // direct streaming loop: no line index, no scratch — the
        // fastest path for one core and the reference semantics for the
        // parity tests.  Batches are line-atomic: when a line's rows
        // (up to two — in + out evaluation) don't fit, it stays
        // unconsumed and opens the next batch, exactly like the Python
        // _TextSource.
        LocalCtx cx{&pk->resolve, {}};
        const char* p = buf;
        int64_t lines = 0, valid = 0;
        int64_t parsed = 0, skipped = 0;
        while (p < end && lines < max_lines) {
            const char* nl = (const char*)memchr(p, '\n', end - p);
            const char* le = nl ? nl : end;
            if (!nl && !final_) break;  // incomplete tail line
            int n = handle_line(&cx, p, le, out, cap, valid);
            if (n < 0) break;  // rows don't fit: close batch, keep line
            if (n == 0) ++skipped;
            else { valid += n; parsed += n; }
            ++lines;
            p = nl ? nl + 1 : end;
        }
        pk->parsed += parsed;
        pk->skipped += skipped;
        zero_tail(out, cap, valid);
        *n_lines_out = lines;
        *n_valid_out = valid;
        return p - buf;
    }

    // ---- pass 1: line-offset index (off[i] = start of line i; off[L] =
    // one past the consumed region)
    std::vector<uint32_t> off;
    off.reserve((size_t)(want > 0 ? want + 1 : 1));
    const char* p = buf;
    while (p < end && (int64_t)off.size() < want) {
        const char* nl = (const char*)memchr(p, '\n', end - p);
        if (!nl && !final_) break;  // incomplete tail line
        off.push_back((uint32_t)(p - buf));
        p = nl ? nl + 1 : end;
    }
    const int64_t L = (int64_t)off.size();
    if (L == 0) {
        zero_tail(out, cap, 0);  // same "padding rows are zero" contract
        *n_lines_out = 0;
        *n_valid_out = 0;
        return 0;
    }
    const int64_t consumed = p - buf;
    off.push_back((uint32_t)consumed);
    // line i spans [buf+off[i], buf+off[i+1]) minus the trailing '\n'
    auto line_end = [&](int64_t i) {
        const char* q = buf + off[i + 1];
        return (q > buf + off[i] && q[-1] == '\n') ? q - 1 : q;
    };

    int W = n_threads;
    if (W <= 0) W = (int)std::thread::hardware_concurrency();
    if (W < 1) W = 1;
    if (W > (int)(L / 1024) + 1) W = (int)(L / 1024) + 1;  // tiny batches: few

    // ---- workers: private slabs (2 rows per line: a connection line can
    // emit both an in- and an out-evaluation), thread-local contexts.
    // rows_per_line records each line's emission count so the compaction
    // can re-apply the line-atomic row cap exactly as the sequential loop
    // (and the Python _TextSource) would.
    std::vector<uint32_t> scratch((size_t)(TUPLE_COLS * 2 * L));
    std::vector<uint8_t> rows_per_line((size_t)L);
    std::vector<int64_t> lo(W + 1);
    for (int w = 0; w <= W; ++w) lo[w] = L * w / W;
    std::vector<LocalCtx> ctx((size_t)W);
    std::vector<std::thread> threads;
    threads.reserve((size_t)W);
    for (int w = 0; w < W; ++w) {
        ctx[w].resolve = &pk->resolve;
        threads.emplace_back([&, w]() {
            const int64_t i0 = lo[w], i1 = lo[w + 1];
            const int64_t slab_cap = 2 * (i1 - i0);
            uint32_t* slab = scratch.data() + (size_t)(2 * i0 * TUPLE_COLS);
            LocalCtx* cx = &ctx[w];
            int64_t v = 0;
            for (int64_t i = i0; i < i1; ++i) {
                int n = handle_line(cx, buf + off[i], line_end(i), slab, slab_cap, v);
                // n < 0 impossible: slab_cap == 2 * range lines
                rows_per_line[(size_t)i] = (uint8_t)(n > 0 ? n : 0);
                if (n > 0) v += n;
            }
        });
    }
    for (auto& t : threads) t.join();

    // ---- line-atomic row cap: consume lines 0..K-1, K maximal with the
    // cumulative rows fitting in cap (the first non-fitting valid line
    // closes the batch, exactly like the sequential loop)
    int64_t K = 0, total_rows = 0;
    int64_t parsed = 0, skipped = 0;
    for (; K < L; ++K) {
        const int64_t r = rows_per_line[(size_t)K];
        if (total_rows + r > cap) break;
        total_rows += r;
        if (r == 0) ++skipped; else parsed += r;
    }

    // ---- compaction: concatenate consumed lines' rows, preserving order
    int64_t valid = 0;
    for (int w = 0; w < W && lo[w] < K; ++w) {
        const int64_t i0 = lo[w], i1 = lo[w + 1] < K ? lo[w + 1] : K;
        const int64_t slab_cap = 2 * (lo[w + 1] - i0);
        const uint32_t* slab = scratch.data() + (size_t)(2 * i0 * TUPLE_COLS);
        int64_t take = 0;  // rows of this worker's consumed lines
        for (int64_t i = i0; i < i1; ++i) take += rows_per_line[(size_t)i];
        for (int64_t c = 0; c < TUPLE_COLS; ++c)
            memcpy(out + c * cap + valid, slab + c * slab_cap,
                   (size_t)take * sizeof(uint32_t));
        valid += take;
    }
    pk->parsed += parsed;
    pk->skipped += skipped;
    zero_tail(out, cap, valid);
    *n_lines_out = K;
    *n_valid_out = valid;
    return K < L ? (int64_t)off[K] : consumed;
}

// Single-threaded ABI kept for compatibility.
int64_t asa_pack_chunk(void* h, const char* buf, int64_t len, int final_,
                       int64_t max_lines, uint32_t* out, int64_t cap,
                       int64_t* n_lines_out, int64_t* n_valid_out) {
    return asa_pack_chunk_mt(h, buf, len, final_, max_lines, out, cap,
                             n_lines_out, n_valid_out, 1);
}

// Dual-family chunk parse (v6-capable rulesets): v4 rows pack into the
// [TUPLE_COLS, cap] plane exactly as asa_pack_chunk, v6 rows into the
// [13, cap6] TUPLE6 plane (limb layout, pack.py).  Callers size
// cap6 >= 2 * max_lines so the v6 side never closes a batch (mirrors
// the Python _TextSource, whose v6 rows ride a side buffer and never
// close a batch either).  ``n_threads`` splits the parse across workers
// with the same slab/compaction structure as asa_pack_chunk_mt —
// output, counters, and consumed bytes are bit-identical for any
// thread count.  Returns bytes consumed.
int64_t asa_pack_chunk2(void* h, const char* buf, int64_t len, int final_,
                        int64_t max_lines, uint32_t* out, int64_t cap,
                        uint32_t* out6, int64_t cap6,
                        int64_t* n_lines_out, int64_t* n_valid_out,
                        int64_t* n_valid6_out, int n_threads) {
    constexpr int64_t T6 = 13;  // TUPLE6_COLS
    Packer* pk = (Packer*)h;
    const char* end = buf + len;
    int64_t want = max_lines < cap ? max_lines : cap;
    if (n_threads != 1 && (len > (int64_t)0xFFFFFFFF || max_lines > cap))
        n_threads = 1;  // same constraints as the v4 MT path

    if (n_threads == 1) {
        LocalCtx cx{&pk->resolve, {}};
        const char* p = buf;
        int64_t lines = 0, valid = 0, valid6 = 0;
        int64_t parsed = 0, skipped = 0;
        while (p < end && lines < max_lines) {
            const char* nl = (const char*)memchr(p, '\n', end - p);
            const char* le = nl ? nl : end;
            if (!nl && !final_) break;  // incomplete tail line
            int64_t v6_before = valid6;
            int n = handle_line(&cx, p, le, out, cap, valid, out6, cap6, &valid6);
            if (n < 0) break;  // rows don't fit: close batch, keep line
            if (n == 0) ++skipped;
            else {
                parsed += n;
                if (valid6 == v6_before) valid += n;  // v4 rows advanced
            }
            ++lines;
            p = nl ? nl + 1 : end;
        }
        pk->parsed += parsed;
        pk->skipped += skipped;
        zero_tail(out, cap, valid);
        for (int64_t c = 0; c < T6; ++c)
            memset(out6 + c * cap6 + valid6, 0,
                   (size_t)(cap6 - valid6) * sizeof(uint32_t));
        *n_lines_out = lines;
        *n_valid_out = valid;
        *n_valid6_out = valid6;
        return p - buf;
    }

    // ---- pass 1: line-offset index (as asa_pack_chunk_mt)
    std::vector<uint32_t> off;
    off.reserve((size_t)(want > 0 ? want + 1 : 1));
    const char* p = buf;
    while (p < end && (int64_t)off.size() < want) {
        const char* nl = (const char*)memchr(p, '\n', end - p);
        if (!nl && !final_) break;
        off.push_back((uint32_t)(p - buf));
        p = nl ? nl + 1 : end;
    }
    const int64_t L = (int64_t)off.size();
    if (L == 0) {
        zero_tail(out, cap, 0);
        for (int64_t c = 0; c < T6; ++c)
            memset(out6 + c * cap6, 0, (size_t)cap6 * sizeof(uint32_t));
        *n_lines_out = 0;
        *n_valid_out = 0;
        *n_valid6_out = 0;
        return 0;
    }
    const int64_t consumed = p - buf;
    off.push_back((uint32_t)consumed);
    auto line_end = [&](int64_t i) {
        const char* q = buf + off[i + 1];
        return (q > buf + off[i] && q[-1] == '\n') ? q - 1 : q;
    };

    int W = n_threads;
    if (W <= 0) W = (int)std::thread::hardware_concurrency();
    if (W < 1) W = 1;
    if (W > (int)(L / 1024) + 1) W = (int)(L / 1024) + 1;

    // ---- workers: private slabs per family + per-line row counts
    std::vector<uint32_t> scratch4((size_t)(TUPLE_COLS * 2 * L));
    std::vector<uint32_t> scratch6((size_t)(T6 * 2 * L));
    std::vector<uint8_t> rows4_per_line((size_t)L);
    std::vector<uint8_t> rows6_per_line((size_t)L);
    std::vector<int64_t> lo(W + 1);
    for (int w = 0; w <= W; ++w) lo[w] = L * w / W;
    std::vector<LocalCtx> ctx((size_t)W);
    std::vector<std::thread> threads;
    threads.reserve((size_t)W);
    for (int w = 0; w < W; ++w) {
        ctx[w].resolve = &pk->resolve;
        threads.emplace_back([&, w]() {
            const int64_t i0 = lo[w], i1 = lo[w + 1];
            const int64_t slab_cap = 2 * (i1 - i0);
            uint32_t* slab4 = scratch4.data() + (size_t)(2 * i0 * TUPLE_COLS);
            uint32_t* slab6 = scratch6.data() + (size_t)(2 * i0 * T6);
            LocalCtx* cx = &ctx[w];
            int64_t v4 = 0, v6 = 0;
            for (int64_t i = i0; i < i1; ++i) {
                int64_t v6_before = v6;
                int n = handle_line(cx, buf + off[i], line_end(i),
                                    slab4, slab_cap, v4,
                                    slab6, slab_cap, &v6);
                // n < 0 impossible: slab caps are 2 * range lines
                if (n > 0 && v6 != v6_before) {
                    rows6_per_line[(size_t)i] = (uint8_t)n;
                } else {
                    rows4_per_line[(size_t)i] = (uint8_t)(n > 0 ? n : 0);
                    if (n > 0) v4 += n;
                }
            }
        });
    }
    for (auto& t : threads) t.join();

    // ---- line-atomic cap on the v4 plane only (cap6 >= 2*max_lines by
    // the caller contract, so v6 rows can never close the batch)
    int64_t K = 0, total4 = 0;
    int64_t parsed = 0, skipped = 0;
    for (; K < L; ++K) {
        const int64_t r4 = rows4_per_line[(size_t)K];
        const int64_t r6 = rows6_per_line[(size_t)K];
        if (total4 + r4 > cap) break;
        total4 += r4;
        if (r4 == 0 && r6 == 0) ++skipped;
        else parsed += r4 + r6;
    }

    // ---- compaction: per family, concatenating consumed lines' rows
    int64_t valid = 0, valid6 = 0;
    for (int w = 0; w < W && lo[w] < K; ++w) {
        const int64_t i0 = lo[w], i1 = lo[w + 1] < K ? lo[w + 1] : K;
        const int64_t slab_cap = 2 * (lo[w + 1] - i0);
        const uint32_t* slab4 = scratch4.data() + (size_t)(2 * i0 * TUPLE_COLS);
        const uint32_t* slab6 = scratch6.data() + (size_t)(2 * i0 * T6);
        int64_t take4 = 0, take6 = 0;
        for (int64_t i = i0; i < i1; ++i) {
            take4 += rows4_per_line[(size_t)i];
            take6 += rows6_per_line[(size_t)i];
        }
        for (int64_t c = 0; c < TUPLE_COLS; ++c)
            memcpy(out + c * cap + valid, slab4 + c * slab_cap,
                   (size_t)take4 * sizeof(uint32_t));
        for (int64_t c = 0; c < T6; ++c)
            memcpy(out6 + c * cap6 + valid6, slab6 + c * slab_cap,
                   (size_t)take6 * sizeof(uint32_t));
        valid += take4;
        valid6 += take6;
    }
    pk->parsed += parsed;
    pk->skipped += skipped;
    zero_tail(out, cap, valid);
    for (int64_t c = 0; c < T6; ++c)
        memset(out6 + c * cap6 + valid6, 0,
               (size_t)(cap6 - valid6) * sizeof(uint32_t));
    *n_lines_out = K;
    *n_valid_out = valid;
    *n_valid6_out = valid6;
    return K < L ? (int64_t)off[K] : consumed;
}

// Plain newline count (streaming buffer bookkeeping; memchr is ~5-10x
// faster than Python-level bytes.count here).
int64_t asa_count_nl(const char* buf, int64_t len) {
    int64_t n = 0;
    const char* p = buf;
    const char* end = buf + len;
    while ((p = (const char*)memchr(p, '\n', end - p)) != nullptr) {
        ++n;
        ++p;
    }
    return n;
}

// Count newline-terminated lines in buf (resume fast-skip helper).
int64_t asa_count_lines(const char* buf, int64_t len, int final_,
                        int64_t max_lines, int64_t* bytes_out) {
    const char* p = buf;
    const char* end = buf + len;
    int64_t lines = 0;
    while (p < end && lines < max_lines) {
        const char* nl = (const char*)memchr(p, '\n', end - p);
        if (!nl && !final_) break;
        ++lines;
        p = nl ? nl + 1 : end;
    }
    *bytes_out = p - buf;
    return lines;
}

// Flow coalescing (ISSUE 5): compact a column-major [rows, b] uint32
// plane into (unique column, summed weight) pairs in FIRST-OCCURRENCE
// order.  The LAST row is the weight/valid plane — zero-weight columns
// drop, the rest group by the remaining rows' values.  One linear pass
// with an open-addressing (linear-probe) table sized to the next power
// of two >= 2b; `out` must have capacity rows*b (laid out [rows, b] —
// the caller slices [:, :U]); `first_idx` (optional) receives each
// unique column's first source index.  Returns U.  ASA flow logs repeat
// the same 5-tuple across 106100/302013 lines, so U << b on real
// traffic — the MapReduce-combiner move applied to the device batch.
int64_t asa_coalesce(const uint32_t* in, int64_t rows, int64_t b,
                     uint32_t* out, int64_t* first_idx) {
    if (rows < 2 || b <= 0) return 0;
    const int64_t krows = rows - 1;
    const uint32_t* wrow = in + krows * b;
    int64_t nslots = 1;
    while (nslots < 2 * b) nslots <<= 1;
    std::vector<int64_t> table((size_t)nslots, -1);
    int64_t u = 0;
    for (int64_t j = 0; j < b; ++j) {
        uint32_t w = wrow[j];
        if (!w) continue;
        uint64_t h = 1469598103934665603ull;  // FNV-1a over the key rows
        for (int64_t r = 0; r < krows; ++r) {
            h ^= in[r * b + j];
            h *= 1099511628211ull;
        }
        h ^= h >> 32;  // fold: the table mask only sees the low bits
        int64_t s = (int64_t)(h & (uint64_t)(nslots - 1));
        for (;;) {
            int64_t p = table[(size_t)s];
            if (p < 0) {
                table[(size_t)s] = u;
                for (int64_t r = 0; r < krows; ++r) out[r * b + u] = in[r * b + j];
                out[krows * b + u] = w;
                if (first_idx) first_idx[u] = j;
                ++u;
                break;
            }
            bool eq = true;
            for (int64_t r = 0; r < krows; ++r) {
                if (out[r * b + p] != in[r * b + j]) { eq = false; break; }
            }
            if (eq) {
                out[krows * b + p] += w;
                break;
            }
            s = (s + 1) & (nslots - 1);
        }
    }
    return u;
}

}  // extern "C"
