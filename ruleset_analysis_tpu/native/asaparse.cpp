// Native ASA syslog parser + tuple packer (the host-side hot loop).
//
// SURVEY.md §8.2 names host-side syslog parsing as the end-to-end
// bottleneck at target rates: the device pipeline sustains millions of
// lines/sec/chip, so a Python regex parser starves it.  This library is
// the native tier of the runtime: it parses raw ASA syslog bytes and
// packs valid lines directly into the column-major [TUPLE_COLS, B]
// uint32 batch layout the device step consumes — one pass, no Python
// objects, no regex engine.
//
// Semantics mirror ruleset_analysis_tpu/hostside/syslog.py (parse_line)
// and pack.py (LinePacker) exactly; tests/test_fastparse.py asserts the
// two paths produce identical batches on synthetic and edge-case
// corpora.  Both paths skip lines whose IPv4 octets, ports (> 65535) or
// protocol numbers (> 255) exceed their field widths.
//
// SIMD layout (ISSUE 11): the line parser body lives in
// asaparse_line.inl and compiles once per ISA — the scalar reference
// here, AVX2 in asaparse_avx2.cpp, NEON in asaparse_neon.cpp — with the
// ISA's scan kernels inlined into the tokenizer loops.  This TU owns the
// runtime dispatch (CPU probe, RA_SIMD override, asa_simd_set A/B
// switch): chunk loops resolve ONE handle-line pointer per call and the
// bulk newline scans go through the ra_simd::ScanOps table.  Outputs are
// byte-identical across every dispatch state (the 12k mutant sweep in
// tests/test_fastparse.py pins it).
//
// C ABI only (loaded via ctypes; no pybind11 in this image).

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "asaparse_types.h"
#include "simd_scan.h"

// ---------------------------------------------------------------------------
// Scalar scan kernels for the reference build of the line parser: plain
// byte loops (the compiler may auto-vectorize under -march=native, but
// the SEMANTICS are the reference), and a dotted-quad hook that always
// defers to the inline scalar parse.
// ---------------------------------------------------------------------------

static inline const char* ra_scan_token_end(const char* p, const char* end) {
    while (p < end &&
           !(*p == ' ' || *p == '\t' || *p == '\v' || *p == '\f' ||
             *p == '\r' || *p == '\n'))
        ++p;
    return p;
}

static inline const char* ra_scan_addr_end(const char* p, const char* end) {
    while (p < end &&
           ((*p >= '0' && *p <= '9') || (*p >= 'a' && *p <= 'f') ||
            (*p >= 'A' && *p <= 'F') || *p == ':' || *p == '.'))
        ++p;
    return p;
}

static inline int ra_scan_ipv4(const char** pp, const char* end,
                               uint32_t* out) {
    (void)pp;
    (void)end;
    (void)out;
    return -1;  // always use the inline scalar reference parse
}

#define RA_PARSE_NS ra_scalar
#include "asaparse_line.inl"
#undef RA_PARSE_NS

namespace ra_parse {
HandleLineFn scalar_handle_line() { return &ra_scalar::handle_line; }
}  // namespace ra_parse

namespace {

using ra_parse::HandleLineFn;
using ra_parse::LocalCtx;
using ra_parse::Packer;

constexpr int64_t TUPLE_COLS = 7;

// ---------------------------------------------------------------------------
// Runtime SIMD dispatch: ONE handle-line pointer (whole-line parser,
// per-ISA build) plus one ScanOps table (bulk newline scans).  Selected
// once per process from the CPU probe; RA_SIMD=off/0/false forces
// scalar, asa_simd_set() flips at runtime so one process can A/B both
// sides of the identity sweep and the feedscale bench.
// ---------------------------------------------------------------------------

std::atomic<HandleLineFn> g_handle{nullptr};
std::atomic<const ra_simd::ScanOps*> g_scan_ops{nullptr};
std::once_flag g_simd_once;

void pick_dispatch(bool simd_on) {
    HandleLineFn h = nullptr;
    const ra_simd::ScanOps* o = nullptr;
    if (simd_on) {
        h = ra_parse::avx2_handle_line();
        if (!h) h = ra_parse::neon_handle_line();
        o = ra_simd::avx2_ops();
        if (!o) o = ra_simd::neon_ops();
    }
    g_handle.store(h ? h : ra_parse::scalar_handle_line(),
                   std::memory_order_relaxed);
    g_scan_ops.store(o, std::memory_order_relaxed);
}

void simd_init() {
    std::call_once(g_simd_once, [] {
        const char* e = std::getenv("RA_SIMD");
        bool off = e && (strcmp(e, "off") == 0 || strcmp(e, "0") == 0 ||
                         strcmp(e, "false") == 0);
        pick_dispatch(!off);
    });
}

inline HandleLineFn handle_line_fn() {
    return g_handle.load(std::memory_order_relaxed);
}

inline const ra_simd::ScanOps* scan_ops() {
    return g_scan_ops.load(std::memory_order_relaxed);
}

// Build the line-start index for the MT parse paths: up to ``want``
// complete lines from [buf, buf+len), plus the trailing unterminated
// fragment as a final line when ``final_``.  Pushes each line's start
// offset onto ``off`` and returns one past the consumed region.  The
// SIMD path gathers every newline position in bulk (32 bytes/cycle of
// classify+movemask) instead of one memchr call per line.
const char* build_line_index(const char* buf, int64_t len, int final_,
                             int64_t want, std::vector<uint32_t>& off) {
    const char* end = buf + len;
    const char* p = buf;
    const ra_simd::ScanOps* ops = scan_ops();
    if (ops && want > 0) {
        std::vector<uint32_t> nls((size_t)want);
        int64_t c = ops->nl_positions(buf, len, nls.data(), want);
        uint32_t start = 0;
        for (int64_t i = 0; i < c; ++i) {
            off.push_back(start);
            start = nls[(size_t)i] + 1;
        }
        p = buf + start;
        if (c < want && p < end && final_) {  // trailing fragment
            off.push_back(start);
            p = end;
        }
        return p;
    }
    while (p < end && (int64_t)off.size() < want) {
        const char* nl = (const char*)memchr(p, '\n', end - p);
        if (!nl && !final_) break;  // incomplete tail line
        off.push_back((uint32_t)(p - buf));
        p = nl ? nl + 1 : end;
    }
    return p;
}

}  // namespace

extern "C" {

void* asa_packer_new() {
    simd_init();
    return new Packer();
}

void asa_packer_free(void* h) { delete (Packer*)h; }

void asa_packer_add_acl(void* h, const char* fw, const char* acl, uint32_t gid) {
    Packer* pk = (Packer*)h;
    std::string k(fw);
    k.push_back('\x01');
    k += acl;
    pk->resolve[k] = gid;
}

void asa_packer_add_binding(void* h, const char* fw, const char* iface, uint32_t gid) {
    Packer* pk = (Packer*)h;
    std::string k(fw);
    k.push_back('\x02');
    k += iface;
    pk->resolve[k] = gid;
}

// out-direction access-group: (firewall, egress interface) -> acl gid.
void asa_packer_add_binding_out(void* h, const char* fw, const char* iface, uint32_t gid) {
    Packer* pk = (Packer*)h;
    std::string k(fw);
    k.push_back('\x03');
    k += iface;
    pk->resolve[k] = gid;
}

int64_t asa_packer_parsed(void* h) { return ((Packer*)h)->parsed; }
int64_t asa_packer_skipped(void* h) { return ((Packer*)h)->skipped; }
void asa_packer_set_counts(void* h, int64_t parsed, int64_t skipped) {
    ((Packer*)h)->parsed = parsed;
    ((Packer*)h)->skipped = skipped;
}

// Zero the padding rows [valid, cap) of every column.  Callers allocate
// the output uninitialized (np.empty); the contract is "padding rows are
// all-zero", matching the pure-Python LinePacker exactly while memsetting
// only the (usually small) tail instead of the whole 28 MB buffer.
void zero_tail(uint32_t* out, int64_t cap, int64_t valid) {
    for (int64_t c = 0; c < TUPLE_COLS; ++c)
        memset(out + c * cap + valid, 0, (size_t)(cap - valid) * sizeof(uint32_t));
}

// Parse up to max_lines newline-terminated lines from buf[0:len) into the
// column-major uint32 out[TUPLE_COLS][cap], using up to n_threads parse
// workers over contiguous line ranges.  With final==0 a trailing fragment
// without '\n' is left unconsumed; with final!=0 it is parsed as the last
// line.  Returns bytes consumed; *n_lines_out lines were consumed,
// *n_valid_out tuples written (rows 0..n_valid-1; rows beyond are zero).
//
// Parallel structure (SURVEY.md §2 L2 — the input-split analog): one
// newline-scan pass builds the line-offset index; lines split evenly
// across workers; each worker parses its range into a private
// column-major slab with a thread-local context; a sequential compaction
// then concatenates the slabs' valid rows in range order.  The output —
// tuple order, counts, consumed bytes — is bit-identical to the
// single-threaded parse.
int64_t asa_pack_chunk_mt(void* h, const char* buf, int64_t len, int final_,
                          int64_t max_lines, uint32_t* out, int64_t cap,
                          int64_t* n_lines_out, int64_t* n_valid_out,
                          int n_threads) {
    simd_init();
    Packer* pk = (Packer*)h;
    const char* end = buf + len;
    int64_t want = max_lines < cap ? max_lines : cap;
    const HandleLineFn handle = handle_line_fn();

    // the parallel path indexes lines with uint32 offsets, and its
    // even-line split can't honor the "keep consuming raw lines while
    // valid < cap" contract that binds when max_lines > cap — route both
    // cases through the exact sequential loop
    if (n_threads != 1 && (len > (int64_t)0xFFFFFFFF || max_lines > cap))
        n_threads = 1;

    if (n_threads == 1) {
        // direct streaming loop: no line index, no scratch — the
        // fastest path for one core and the reference semantics for the
        // parity tests.  Batches are line-atomic: when a line's rows
        // (up to two — in + out evaluation) don't fit, it stays
        // unconsumed and opens the next batch, exactly like the Python
        // _TextSource.
        LocalCtx cx{&pk->resolve, {}};
        const char* p = buf;
        int64_t lines = 0, valid = 0;
        int64_t parsed = 0, skipped = 0;
        while (p < end && lines < max_lines) {
            const char* nl = (const char*)memchr(p, '\n', end - p);
            const char* le = nl ? nl : end;
            if (!nl && !final_) break;  // incomplete tail line
            int n = handle(&cx, p, le, out, cap, valid, nullptr, 0, nullptr);
            if (n < 0) break;  // rows don't fit: close batch, keep line
            if (n == 0) ++skipped;
            else { valid += n; parsed += n; }
            ++lines;
            p = nl ? nl + 1 : end;
        }
        pk->parsed += parsed;
        pk->skipped += skipped;
        zero_tail(out, cap, valid);
        *n_lines_out = lines;
        *n_valid_out = valid;
        return p - buf;
    }

    // ---- pass 1: line-offset index (off[i] = start of line i; off[L] =
    // one past the consumed region)
    std::vector<uint32_t> off;
    off.reserve((size_t)(want > 0 ? want + 1 : 1));
    const char* p = build_line_index(buf, len, final_, want, off);
    const int64_t L = (int64_t)off.size();
    if (L == 0) {
        zero_tail(out, cap, 0);  // same "padding rows are zero" contract
        *n_lines_out = 0;
        *n_valid_out = 0;
        return 0;
    }
    const int64_t consumed = p - buf;
    off.push_back((uint32_t)consumed);
    // line i spans [buf+off[i], buf+off[i+1]) minus the trailing '\n'
    auto line_end = [&](int64_t i) {
        const char* q = buf + off[i + 1];
        return (q > buf + off[i] && q[-1] == '\n') ? q - 1 : q;
    };

    int W = n_threads;
    if (W <= 0) W = (int)std::thread::hardware_concurrency();
    if (W < 1) W = 1;
    if (W > (int)(L / 1024) + 1) W = (int)(L / 1024) + 1;  // tiny batches: few

    // ---- workers: private slabs (2 rows per line: a connection line can
    // emit both an in- and an out-evaluation), thread-local contexts.
    // rows_per_line records each line's emission count so the compaction
    // can re-apply the line-atomic row cap exactly as the sequential loop
    // (and the Python _TextSource) would.
    std::vector<uint32_t> scratch((size_t)(TUPLE_COLS * 2 * L));
    std::vector<uint8_t> rows_per_line((size_t)L);
    std::vector<int64_t> lo(W + 1);
    for (int w = 0; w <= W; ++w) lo[w] = L * w / W;
    std::vector<LocalCtx> ctx((size_t)W);
    std::vector<std::thread> threads;
    threads.reserve((size_t)W);
    for (int w = 0; w < W; ++w) {
        ctx[w].resolve = &pk->resolve;
        threads.emplace_back([&, w]() {
            const int64_t i0 = lo[w], i1 = lo[w + 1];
            const int64_t slab_cap = 2 * (i1 - i0);
            uint32_t* slab = scratch.data() + (size_t)(2 * i0 * TUPLE_COLS);
            LocalCtx* cx = &ctx[w];
            int64_t v = 0;
            for (int64_t i = i0; i < i1; ++i) {
                int n = handle(cx, buf + off[i], line_end(i), slab, slab_cap,
                               v, nullptr, 0, nullptr);
                // n < 0 impossible: slab_cap == 2 * range lines
                rows_per_line[(size_t)i] = (uint8_t)(n > 0 ? n : 0);
                if (n > 0) v += n;
            }
        });
    }
    for (auto& t : threads) t.join();

    // ---- line-atomic row cap: consume lines 0..K-1, K maximal with the
    // cumulative rows fitting in cap (the first non-fitting valid line
    // closes the batch, exactly like the sequential loop)
    int64_t K = 0, total_rows = 0;
    int64_t parsed = 0, skipped = 0;
    for (; K < L; ++K) {
        const int64_t r = rows_per_line[(size_t)K];
        if (total_rows + r > cap) break;
        total_rows += r;
        if (r == 0) ++skipped; else parsed += r;
    }

    // ---- compaction: concatenate consumed lines' rows, preserving order
    int64_t valid = 0;
    for (int w = 0; w < W && lo[w] < K; ++w) {
        const int64_t i0 = lo[w], i1 = lo[w + 1] < K ? lo[w + 1] : K;
        const int64_t slab_cap = 2 * (lo[w + 1] - i0);
        const uint32_t* slab = scratch.data() + (size_t)(2 * i0 * TUPLE_COLS);
        int64_t take = 0;  // rows of this worker's consumed lines
        for (int64_t i = i0; i < i1; ++i) take += rows_per_line[(size_t)i];
        for (int64_t c = 0; c < TUPLE_COLS; ++c)
            memcpy(out + c * cap + valid, slab + c * slab_cap,
                   (size_t)take * sizeof(uint32_t));
        valid += take;
    }
    pk->parsed += parsed;
    pk->skipped += skipped;
    zero_tail(out, cap, valid);
    *n_lines_out = K;
    *n_valid_out = valid;
    return K < L ? (int64_t)off[K] : consumed;
}

// Single-threaded ABI kept for compatibility.
int64_t asa_pack_chunk(void* h, const char* buf, int64_t len, int final_,
                       int64_t max_lines, uint32_t* out, int64_t cap,
                       int64_t* n_lines_out, int64_t* n_valid_out) {
    return asa_pack_chunk_mt(h, buf, len, final_, max_lines, out, cap,
                             n_lines_out, n_valid_out, 1);
}

// Dual-family chunk parse (v6-capable rulesets): v4 rows pack into the
// [TUPLE_COLS, cap] plane exactly as asa_pack_chunk, v6 rows into the
// [13, cap6] TUPLE6 plane (limb layout, pack.py).  Callers size
// cap6 >= 2 * max_lines so the v6 side never closes a batch (mirrors
// the Python _TextSource, whose v6 rows ride a side buffer and never
// close a batch either).  ``n_threads`` splits the parse across workers
// with the same slab/compaction structure as asa_pack_chunk_mt —
// output, counters, and consumed bytes are bit-identical for any
// thread count.  Returns bytes consumed.
int64_t asa_pack_chunk2(void* h, const char* buf, int64_t len, int final_,
                        int64_t max_lines, uint32_t* out, int64_t cap,
                        uint32_t* out6, int64_t cap6,
                        int64_t* n_lines_out, int64_t* n_valid_out,
                        int64_t* n_valid6_out, int n_threads) {
    simd_init();
    constexpr int64_t T6 = 13;  // TUPLE6_COLS
    Packer* pk = (Packer*)h;
    const char* end = buf + len;
    int64_t want = max_lines < cap ? max_lines : cap;
    const HandleLineFn handle = handle_line_fn();
    if (n_threads != 1 && (len > (int64_t)0xFFFFFFFF || max_lines > cap))
        n_threads = 1;  // same constraints as the v4 MT path

    if (n_threads == 1) {
        LocalCtx cx{&pk->resolve, {}};
        const char* p = buf;
        int64_t lines = 0, valid = 0, valid6 = 0;
        int64_t parsed = 0, skipped = 0;
        while (p < end && lines < max_lines) {
            const char* nl = (const char*)memchr(p, '\n', end - p);
            const char* le = nl ? nl : end;
            if (!nl && !final_) break;  // incomplete tail line
            int64_t v6_before = valid6;
            int n = handle(&cx, p, le, out, cap, valid, out6, cap6, &valid6);
            if (n < 0) break;  // rows don't fit: close batch, keep line
            if (n == 0) ++skipped;
            else {
                parsed += n;
                if (valid6 == v6_before) valid += n;  // v4 rows advanced
            }
            ++lines;
            p = nl ? nl + 1 : end;
        }
        pk->parsed += parsed;
        pk->skipped += skipped;
        zero_tail(out, cap, valid);
        for (int64_t c = 0; c < T6; ++c)
            memset(out6 + c * cap6 + valid6, 0,
                   (size_t)(cap6 - valid6) * sizeof(uint32_t));
        *n_lines_out = lines;
        *n_valid_out = valid;
        *n_valid6_out = valid6;
        return p - buf;
    }

    // ---- pass 1: line-offset index (as asa_pack_chunk_mt)
    std::vector<uint32_t> off;
    off.reserve((size_t)(want > 0 ? want + 1 : 1));
    const char* p = build_line_index(buf, len, final_, want, off);
    const int64_t L = (int64_t)off.size();
    if (L == 0) {
        zero_tail(out, cap, 0);
        for (int64_t c = 0; c < T6; ++c)
            memset(out6 + c * cap6, 0, (size_t)cap6 * sizeof(uint32_t));
        *n_lines_out = 0;
        *n_valid_out = 0;
        *n_valid6_out = 0;
        return 0;
    }
    const int64_t consumed = p - buf;
    off.push_back((uint32_t)consumed);
    auto line_end = [&](int64_t i) {
        const char* q = buf + off[i + 1];
        return (q > buf + off[i] && q[-1] == '\n') ? q - 1 : q;
    };

    int W = n_threads;
    if (W <= 0) W = (int)std::thread::hardware_concurrency();
    if (W < 1) W = 1;
    if (W > (int)(L / 1024) + 1) W = (int)(L / 1024) + 1;

    // ---- workers: private slabs per family + per-line row counts
    std::vector<uint32_t> scratch4((size_t)(TUPLE_COLS * 2 * L));
    std::vector<uint32_t> scratch6((size_t)(T6 * 2 * L));
    std::vector<uint8_t> rows4_per_line((size_t)L);
    std::vector<uint8_t> rows6_per_line((size_t)L);
    std::vector<int64_t> lo(W + 1);
    for (int w = 0; w <= W; ++w) lo[w] = L * w / W;
    std::vector<LocalCtx> ctx((size_t)W);
    std::vector<std::thread> threads;
    threads.reserve((size_t)W);
    for (int w = 0; w < W; ++w) {
        ctx[w].resolve = &pk->resolve;
        threads.emplace_back([&, w]() {
            const int64_t i0 = lo[w], i1 = lo[w + 1];
            const int64_t slab_cap = 2 * (i1 - i0);
            uint32_t* slab4 = scratch4.data() + (size_t)(2 * i0 * TUPLE_COLS);
            uint32_t* slab6 = scratch6.data() + (size_t)(2 * i0 * T6);
            LocalCtx* cx = &ctx[w];
            int64_t v4 = 0, v6 = 0;
            for (int64_t i = i0; i < i1; ++i) {
                int64_t v6_before = v6;
                int n = handle(cx, buf + off[i], line_end(i),
                               slab4, slab_cap, v4,
                               slab6, slab_cap, &v6);
                // n < 0 impossible: slab caps are 2 * range lines
                if (n > 0 && v6 != v6_before) {
                    rows6_per_line[(size_t)i] = (uint8_t)n;
                } else {
                    rows4_per_line[(size_t)i] = (uint8_t)(n > 0 ? n : 0);
                    if (n > 0) v4 += n;
                }
            }
        });
    }
    for (auto& t : threads) t.join();

    // ---- line-atomic cap on the v4 plane only (cap6 >= 2*max_lines by
    // the caller contract, so v6 rows can never close the batch)
    int64_t K = 0, total4 = 0;
    int64_t parsed = 0, skipped = 0;
    for (; K < L; ++K) {
        const int64_t r4 = rows4_per_line[(size_t)K];
        const int64_t r6 = rows6_per_line[(size_t)K];
        if (total4 + r4 > cap) break;
        total4 += r4;
        if (r4 == 0 && r6 == 0) ++skipped;
        else parsed += r4 + r6;
    }

    // ---- compaction: per family, concatenating consumed lines' rows
    int64_t valid = 0, valid6 = 0;
    for (int w = 0; w < W && lo[w] < K; ++w) {
        const int64_t i0 = lo[w], i1 = lo[w + 1] < K ? lo[w + 1] : K;
        const int64_t slab_cap = 2 * (lo[w + 1] - i0);
        const uint32_t* slab4 = scratch4.data() + (size_t)(2 * i0 * TUPLE_COLS);
        const uint32_t* slab6 = scratch6.data() + (size_t)(2 * i0 * T6);
        int64_t take4 = 0, take6 = 0;
        for (int64_t i = i0; i < i1; ++i) {
            take4 += rows4_per_line[(size_t)i];
            take6 += rows6_per_line[(size_t)i];
        }
        for (int64_t c = 0; c < TUPLE_COLS; ++c)
            memcpy(out + c * cap + valid, slab4 + c * slab_cap,
                   (size_t)take4 * sizeof(uint32_t));
        for (int64_t c = 0; c < T6; ++c)
            memcpy(out6 + c * cap6 + valid6, slab6 + c * slab_cap,
                   (size_t)take6 * sizeof(uint32_t));
        valid += take4;
        valid6 += take6;
    }
    pk->parsed += parsed;
    pk->skipped += skipped;
    zero_tail(out, cap, valid);
    for (int64_t c = 0; c < T6; ++c)
        memset(out6 + c * cap6 + valid6, 0,
               (size_t)(cap6 - valid6) * sizeof(uint32_t));
    *n_lines_out = K;
    *n_valid_out = valid;
    *n_valid6_out = valid6;
    return K < L ? (int64_t)off[K] : consumed;
}

// Plain newline count (streaming buffer bookkeeping; the SIMD popcount
// pass beats even libc memchr chaining, and both beat Python-level
// bytes.count by ~5-10x).
int64_t asa_count_nl(const char* buf, int64_t len) {
    simd_init();
    if (const ra_simd::ScanOps* o = scan_ops()) return o->count_nl(buf, len);
    int64_t n = 0;
    const char* p = buf;
    const char* end = buf + len;
    while ((p = (const char*)memchr(p, '\n', end - p)) != nullptr) {
        ++n;
        ++p;
    }
    return n;
}

// Count newline-terminated lines in buf (resume fast-skip helper).
int64_t asa_count_lines(const char* buf, int64_t len, int final_,
                        int64_t max_lines, int64_t* bytes_out) {
    simd_init();
    if (const ra_simd::ScanOps* o = scan_ops()) {
        int64_t bytes = 0;
        int64_t lines = o->nl_skip(buf, len, max_lines, &bytes);
        if (lines < max_lines && bytes < len && final_) {
            // trailing unterminated fragment counts as a line when final
            ++lines;
            bytes = len;
        }
        *bytes_out = bytes;
        return lines;
    }
    const char* p = buf;
    const char* end = buf + len;
    int64_t lines = 0;
    while (p < end && lines < max_lines) {
        const char* nl = (const char*)memchr(p, '\n', end - p);
        if (!nl && !final_) break;
        ++lines;
        p = nl ? nl + 1 : end;
    }
    *bytes_out = p - buf;
    return lines;
}

// SIMD dispatch introspection/override (ISSUE 11): kind is 0 scalar,
// 1 AVX2, 2 NEON; asa_simd_set(0) forces scalar, (1) re-enables the
// detected ISA — the in-process A/B switch the identity sweep and the
// feedscale bench use (RA_SIMD=off is the env-level equivalent).
int asa_simd_kind() {
    simd_init();
    HandleLineFn h = handle_line_fn();
    if (h && h == ra_parse::avx2_handle_line()) return 1;
    if (h && h == ra_parse::neon_handle_line()) return 2;
    return 0;
}

void asa_simd_set(int on) {
    simd_init();
    pick_dispatch(on != 0);
}

// Flow coalescing (ISSUE 5): compact a column-major [rows, b] uint32
// plane into (unique column, summed weight) pairs in FIRST-OCCURRENCE
// order.  The LAST row is the weight/valid plane — zero-weight columns
// drop, the rest group by the remaining rows' values.  One linear pass
// with an open-addressing (linear-probe) table sized to the next power
// of two >= 2b; `out` must have capacity rows*b (laid out [rows, b] —
// the caller slices [:, :U]); `first_idx` (optional) receives each
// unique column's first source index.  Returns U.  ASA flow logs repeat
// the same 5-tuple across 106100/302013 lines, so U << b on real
// traffic — the MapReduce-combiner move applied to the device batch.
int64_t asa_coalesce(const uint32_t* in, int64_t rows, int64_t b,
                     uint32_t* out, int64_t* first_idx) {
    if (rows < 2 || b <= 0) return 0;
    const int64_t krows = rows - 1;
    const uint32_t* wrow = in + krows * b;
    int64_t nslots = 1;
    while (nslots < 2 * b) nslots <<= 1;
    std::vector<int64_t> table((size_t)nslots, -1);
    int64_t u = 0;
    for (int64_t j = 0; j < b; ++j) {
        uint32_t w = wrow[j];
        if (!w) continue;
        uint64_t h = 1469598103934665603ull;  // FNV-1a over the key rows
        for (int64_t r = 0; r < krows; ++r) {
            h ^= in[r * b + j];
            h *= 1099511628211ull;
        }
        h ^= h >> 32;  // fold: the table mask only sees the low bits
        int64_t s = (int64_t)(h & (uint64_t)(nslots - 1));
        for (;;) {
            int64_t p = table[(size_t)s];
            if (p < 0) {
                table[(size_t)s] = u;
                for (int64_t r = 0; r < krows; ++r) out[r * b + u] = in[r * b + j];
                out[krows * b + u] = w;
                if (first_idx) first_idx[u] = j;
                ++u;
                break;
            }
            bool eq = true;
            for (int64_t r = 0; r < krows; ++r) {
                if (out[r * b + p] != in[r * b + j]) { eq = false; break; }
            }
            if (eq) {
                out[krows * b + p] += w;
                break;
            }
            s = (s + 1) & (nslots - 1);
        }
    }
    return u;
}

}  // extern "C"
