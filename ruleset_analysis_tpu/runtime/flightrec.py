"""Always-on flight recorder + crash forensics (DESIGN §20).

Every observability surface before this PR was opt-in: an unarmed
production ``run``/``serve`` that hit a typed abort, a watchdog stall
(exit 6), or a SIGKILL'd worker left behind an exit code and nothing
else.  This module is the black box that is ALWAYS recording:

- **Ring.**  Each process keeps a fixed-size, pre-allocated in-memory
  ring of recent telemetry events, overwritten in place — span
  begin/ends and instants sampled from the existing ``obs.py`` emit
  path (the tap is one module-global ``None`` check per event), metrics
  snapshot records, fault/retry/degraded instants, plus a small cursor
  dict (last committed batch, checkpoint/WAL seq, current window).
  Strictly cheaper than the armed trace plane: NO per-event file I/O —
  the ring only ever touches disk at a dump trigger.

- **Dump triggers** (:data:`TRIGGERS`).  On a typed ``AnalysisError``
  escalation, a watchdog ``StallError``, an unhandled exception
  (``sys.excepthook`` / ``threading.excepthook``), an operator
  ``SIGQUIT``, or an injected ``crash`` fault, the process atomically
  dumps its ring to a per-PID shard (``blackbox-<pid>.json``) under the
  blackbox directory.  Worker processes additionally *seal* their ring
  at exit, so a run that dies can merge the survivors' telemetry too;
  a clean run prunes every shard and leaves nothing behind.

- **Bundle.**  The supervising tier (``cli.main``'s finally) merges all
  shards into one ``postmortem.json`` naming the dump trigger, the
  failing stage, per-stage occupancy over each shard's final ring
  window, queue depths, retry history, the degraded set, and every
  fired fault site.  ``tools/doctor.py`` (and the ``doctor`` CLI
  subcommand) turn a bundle + exit code into a ranked diagnosis;
  ``tools/trace_summary.py`` renders the same bundle as a ``blackbox``
  block.

- **Inheritance.**  :func:`arm` exports :data:`ENV_VAR`
  (``RA_BLACKBOX_DIR``) exactly like ``RA_TRACE_DIR``, so spawned
  feeder workers and elastic generation workers lazily arm their own
  rings on their first ``obs`` activity and participate in the merge.
"""

from __future__ import annotations

import glob
import json
import os
import signal
import sys
import threading
import time

from ..errors import AnalysisError, StallError

#: Environment variable carrying the blackbox directory to child
#: processes (the RA_TRACE_DIR / RA_FAULT_PLAN inheritance discipline).
ENV_VAR = "RA_BLACKBOX_DIR"

#: Kill switch for the CLI's DEFAULT arming (``RA_BLACKBOX=off``): test
#: harnesses set it so incidental CLI invocations don't write forensics
#: into the working tree.  An explicit ``--blackbox-dir`` still arms.
KILL_SWITCH = "RA_BLACKBOX"

#: Events retained per process.  512 events cover the final seconds of
#: any pipeline tier at production batch cadence while bounding the
#: ring's memory to well under a megabyte (DESIGN §20 sizing model).
DEFAULT_RING_EVENTS = 512

#: Registered dump triggers: name -> what fired the dump.  The registry
#: auditor (verify/registry.py::audit_observability) pins every trigger
#: to a dump call site AND a test, so an untested crash path fails
#: ``make lint`` instead of failing an operator.
TRIGGERS: dict[str, str] = {
    "abort": "a typed AnalysisError escalated out of the driver",
    "stall": "a watchdog bounded a hang (StallError, exit code 6)",
    "unhandled": "an untyped exception reached the top of a thread or "
                 "the interpreter (sys/threading excepthook)",
    "signal": "an operator SIGQUIT requested a live forensics snapshot "
              "without stopping the service",
    "crash": "an injected crash fault (faults.py os._exit action) — the "
             "OOM-kill analog dumps its ring before dying",
    "worker-exit": "a worker process sealed its ring at teardown "
                   "(merged only when the supervising run aborts; a "
                   "clean run prunes every seal)",
}


class FlightRing:
    """Fixed-size overwrite-in-place event ring (lock-light).

    Slots are pre-allocated; :meth:`append` is one short critical
    section (slot store + index bump).  Events are Chrome-trace-shaped
    dicts so the merge, ``trace_summary``, and ``doctor`` reuse the
    plane's existing classifiers unchanged.
    """

    def __init__(self, capacity: int = DEFAULT_RING_EVENTS):
        if capacity < 8:
            raise AnalysisError(
                f"flight ring capacity must be >= 8 events, got {capacity}"
            )
        self._slots: list = [None] * capacity
        self._n = 0
        self._lock = threading.Lock()

    def append(self, ev: dict) -> None:
        with self._lock:
            self._slots[self._n % len(self._slots)] = ev
            self._n += 1

    @property
    def total(self) -> int:
        return self._n

    @property
    def capacity(self) -> int:
        return len(self._slots)

    def events(self) -> list[dict]:
        """Retained events, oldest first."""
        with self._lock:
            n, cap = self._n, len(self._slots)
            if n <= cap:
                return [e for e in self._slots[:n] if e is not None]
            i = n % cap
            return [e for e in self._slots[i:] + self._slots[:i] if e is not None]


class _Recorder:
    """One process's armed flight recorder (ring + cursors + identity)."""

    def __init__(self, blackbox_dir: str, role: str, ring_events: int):
        self.dir = os.path.abspath(blackbox_dir)
        self.role = role
        self.pid = os.getpid()
        self.ring = FlightRing(ring_events)
        self.cursors: dict = {}
        self._cur_lock = threading.Lock()
        # one pairing converts perf_counter endpoints to the shared
        # epoch-microsecond axis (the Tracer discipline), so shards from
        # different processes merge onto one timeline
        self._epoch_us = time.time_ns() // 1_000
        self._pc0 = time.perf_counter()
        self.dumped: list[str] = []  # triggers that dumped this run

    def _us(self, pc: float) -> int:
        return self._epoch_us + int((pc - self._pc0) * 1e6)

    # -- the obs tap (hot path; called with the plane disarmed too) ------
    def span(self, name: str, t0_pc: float, t1_pc: float, args=None) -> None:
        ev = {
            "ph": "X",
            "name": name,
            "pid": self.pid,
            "tid": threading.get_native_id(),
            "ts": self._us(t0_pc),
            "dur": max(0, int((t1_pc - t0_pc) * 1e6)),
        }
        if args:
            ev["args"] = args
        self.ring.append(ev)

    def instant(self, name: str, args=None) -> None:
        ev = {
            "ph": "i",
            "name": name,
            "pid": self.pid,
            "tid": threading.get_native_id(),
            "ts": self._us(time.perf_counter()),
        }
        if args:
            ev["args"] = args
        self.ring.append(ev)

    def cursor(self, kw: dict) -> None:
        with self._cur_lock:
            self.cursors.update(kw)

    # -- dump ------------------------------------------------------------
    def shard_path(self) -> str:
        return os.path.join(self.dir, f"blackbox-{self.pid}.json")

    def dump(self, trigger: str, error=None, exit_code=None) -> str:
        """Atomically write this process's shard (idempotent: last wins)."""
        if trigger not in TRIGGERS:
            raise AnalysisError(
                f"unregistered dump trigger {trigger!r}; registered: "
                f"{', '.join(sorted(TRIGGERS))}"
            )
        from . import obs, retrypolicy

        with self._cur_lock:
            cursors = dict(self.cursors)
        shard = {
            "kind": "ra-blackbox-shard",
            "pid": self.pid,
            "role": self.role,
            "trigger": trigger,
            "t_unix": round(time.time(), 3),
            "ring_events": self.ring.events(),
            "ring_total": self.ring.total,
            "ring_capacity": self.ring.capacity,
            "cursors": cursors,
            "samplers": obs.sampler_snapshot(),
            "retry": retrypolicy.counters(),
        }
        if error is not None:
            shard["error"] = str(error)[:500]
            shard["error_type"] = type(error).__name__ if isinstance(
                error, BaseException
            ) else "str"
        if exit_code is not None:
            shard["exit_code"] = int(exit_code)
        os.makedirs(self.dir, exist_ok=True)
        path = self.shard_path()
        tmp = f"{path}.{self.pid}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(shard, f, separators=(",", ":"))
            os.replace(tmp, path)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        self.dumped.append(trigger)
        return path


# ---------------------------------------------------------------------------
# Module arming state (the faults.py / obs.py discipline: `_rec is None`
# is the production fast path; env check runs at most once per process).
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_rec: _Recorder | None = None
_env_exported = False
_env_checked = False
_noted_error: BaseException | None = None
_noted_exit_code: int | None = None
_prev_sys_hook = None
_prev_threading_hook = None
_prev_sigquit = None


def armed() -> bool:
    return _rec is not None


def active() -> _Recorder | None:
    return _rec


def arm(
    blackbox_dir: str,
    *,
    role: str = "main",
    ring_events: int = DEFAULT_RING_EVENTS,
    export_env: bool = True,
) -> _Recorder:
    """Arm the recorder process-wide; idempotent per directory.

    ``export_env`` marks this process the run OWNER: the directory is
    published to :data:`ENV_VAR` for spawned workers, stale shards of
    previous runs are pruned (at dump/merge time the directory is
    created lazily — a clean run never touches disk), and the
    supervising merge happens here.
    """
    global _rec, _env_exported, _env_checked, _noted_error, _noted_exit_code
    with _lock:
        cur = _rec
        if cur is not None and cur.dir == os.path.abspath(blackbox_dir):
            # re-arming the same directory starts a NEW run: forget the
            # previous run's failure state so its finalize can't leak a
            # spurious postmortem into this one's clean exit
            _noted_error = None
            _noted_exit_code = None
            cur.dumped.clear()
            if export_env:
                os.environ[ENV_VAR] = cur.dir
                _env_exported = True
                _prune_stale(cur.dir)
            return cur
        _rec = _Recorder(blackbox_dir, role, ring_events)
        # a new recorder is a new run: any failure noted by a previous
        # run in this process must not leak into this one's finalize
        _noted_error = None
        _noted_exit_code = None
        _env_checked = True
        if export_env:
            os.environ[ENV_VAR] = _rec.dir
            _env_exported = True
            _prune_stale(_rec.dir)
        rec = _rec
    from . import obs

    obs._set_flight(rec)
    _install_hooks()
    return rec


def maybe_arm_from_env() -> None:
    """One-time lazy arm from the inherited environment (spawned workers)."""
    global _env_checked
    with _lock:
        if _env_checked or _rec is not None:
            _env_checked = True
            return
        _env_checked = True
    d = os.environ.get(ENV_VAR, "")
    if d:
        from . import obs

        arm(d, role=obs._role or "worker", export_env=False)


def disarm() -> None:
    global _rec, _env_exported, _noted_error, _noted_exit_code
    with _lock:
        _rec = None
        _noted_error = None
        _noted_exit_code = None
        if _env_exported:
            os.environ.pop(ENV_VAR, None)
            _env_exported = False
    from . import obs

    obs._set_flight(None)


def _reset_for_tests() -> None:
    """Forget arming INCLUDING the once-per-process env check."""
    global _env_checked
    disarm()
    with _lock:
        _env_checked = False


def _prune_stale(blackbox_dir: str) -> None:
    """Remove a previous run's leftovers (shards + merged bundle)."""
    for path in glob.glob(os.path.join(blackbox_dir, "blackbox-*.json")):
        try:
            os.unlink(path)
        except OSError:
            pass
    try:
        os.unlink(os.path.join(blackbox_dir, "postmortem.json"))
    except OSError:
        pass


# -- production call surface (every function below is a no-op disarmed) ----


def cursor(**kw) -> None:
    """Update the last-known-position cursors (committed batch, ckpt/WAL
    seq, current window...) carried in a dump."""
    rec = _rec
    if rec is not None:
        rec.cursor(kw)


def dump(trigger: str, error=None, exit_code=None) -> str | None:
    rec = _rec
    if rec is None:
        return None
    try:
        return rec.dump(trigger, error=error, exit_code=exit_code)
    except OSError:
        return None  # forensics must never mask the failure being recorded


def seal(trigger: str = "worker-exit") -> str | None:
    """Worker-exit seal: dump the ring so a supervising merge can read
    this process's telemetry if the RUN aborts (a clean run prunes it).
    """
    rec = _rec
    if rec is None or rec.ring.total == 0:
        return None
    return dump(trigger)


def classify(exc: BaseException | None) -> str:
    if isinstance(exc, StallError):
        return "stall"
    if isinstance(exc, AnalysisError):
        return "abort"
    return "unhandled"


def note_abort(exc: BaseException | None, exit_code: int) -> None:
    """Record the run's failure for :func:`finalize` (cli error handlers)."""
    global _noted_error, _noted_exit_code
    if _rec is None:
        return
    _noted_error = exc
    _noted_exit_code = exit_code


def note_failure(exit_code: int) -> None:
    """A failure reported by exit code alone (elastic supervisor rc)."""
    note_abort(None, exit_code)


def finalize() -> str | None:
    """End-of-run step for the supervising process (cli.main finally).

    Aborted run (noted, in-flight unhandled exception, or any dump this
    run): dump this process's ring and merge every shard into
    ``postmortem.json``, returning its path.  Clean run: prune the
    shards worker seals left behind — a clean exit leaves none.
    """
    rec = _rec
    if rec is None:
        return None
    exc = _noted_error
    if exc is None:
        exc = sys.exc_info()[1]
        # an operator Ctrl-C / normal interpreter exit is teardown, not
        # a crash: it must not leave forensics claiming a failure
        if isinstance(exc, (KeyboardInterrupt, SystemExit)):
            exc = None
    if exc is None and _noted_exit_code is None and not rec.dumped:
        # clean exit: leave NO forensics behind
        _prune_stale(rec.dir)
        return None
    if exc is not None or _noted_exit_code is not None:
        trigger = classify(exc) if exc is not None else "abort"
        dump(trigger, error=exc, exit_code=_noted_exit_code)
    else:
        trigger = rec.dumped[-1]
    try:
        return merge(
            rec.dir,
            trigger=trigger,
            error=exc,
            exit_code=_noted_exit_code,
        )
    except OSError:
        return None


# ---------------------------------------------------------------------------
# Crash hooks: unhandled exceptions and SIGQUIT.
# ---------------------------------------------------------------------------


def _install_hooks() -> None:
    global _prev_sys_hook, _prev_threading_hook, _prev_sigquit
    if _prev_sys_hook is not None:
        return  # installed once per process

    prev_sys = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            # Ctrl-C / sys.exit are teardown, not crashes
            if not issubclass(exc_type, (KeyboardInterrupt, SystemExit)):
                dump(classify(exc), error=exc)
        except Exception:
            pass
        prev_sys(exc_type, exc, tb)

    _prev_sys_hook = prev_sys
    sys.excepthook = _hook

    prev_thr = threading.excepthook

    def _thr_hook(args):
        try:
            # a dying ra- thread (listener, metrics, producer) seals the
            # moment of death; SystemExit is normal teardown
            if args.exc_type is not SystemExit:
                dump(classify(args.exc_value), error=args.exc_value)
        except Exception:
            pass
        prev_thr(args)

    _prev_threading_hook = prev_thr
    threading.excepthook = _thr_hook

    # SIGQUIT = operator-triggered live snapshot: dump + merge without
    # stopping the process (only installable from the main thread).  The
    # handler runs ON the main thread, which may be inside any of the
    # ring/cursor/sampler critical sections when the signal lands — so the
    # snapshot itself runs on a short-lived thread (it can safely block on
    # those non-reentrant locks; the interrupted frame resumes and
    # releases them as soon as the handler returns).
    snap_inflight = threading.Event()

    def _snapshot():
        try:
            rec = _rec
            if rec is None:
                return
            dump("signal")
            try:
                merge(rec.dir, trigger="signal", error=None, exit_code=None)
            except OSError:
                pass
        finally:
            snap_inflight.clear()

    def _sigquit(_signum, _frame):
        if _rec is None or snap_inflight.is_set():
            return
        snap_inflight.set()
        threading.Thread(
            target=_snapshot, name="ra-blackbox-snap", daemon=True
        ).start()

    try:
        _prev_sigquit = signal.signal(signal.SIGQUIT, _sigquit)
    except (ValueError, OSError, AttributeError):
        _prev_sigquit = None  # non-main thread / platform without SIGQUIT


# ---------------------------------------------------------------------------
# Merge: shards -> one postmortem bundle.
# ---------------------------------------------------------------------------


def stage_occupancy(events: list[dict]) -> dict[str, float]:
    """Per-stage busy % over the events' wall window (ring or trace)."""
    spans = [e for e in events if e.get("ph") == "X" and "ts" in e]
    if not spans:
        return {}
    t_min = min(e["ts"] for e in spans)
    t_max = max(e["ts"] + e.get("dur", 0) for e in spans)
    wall = max(1, t_max - t_min)
    busy: dict[str, int] = {}
    for e in spans:
        busy[e["name"]] = busy.get(e["name"], 0) + e.get("dur", 0)
    return {
        name: round(100.0 * us / wall, 2)
        for name, us in sorted(busy.items(), key=lambda kv: -kv[1])
    }


def _shard_analysis(shard: dict) -> dict:
    events = shard.get("ring_events", [])
    instants = [e for e in events if e.get("ph") == "i"]
    fault_sites: dict[str, int] = {}
    for e in instants:
        name = e.get("name", "")
        if name.startswith("fault."):
            fault_sites[name[len("fault."):]] = (
                fault_sites.get(name[len("fault."):], 0) + 1
            )
    # tenant activity on the final ring window (multi-tenant serve tags
    # rotate/reload/window events with args.tenant): ranks which lane
    # was hot when the process died
    tenant_events: dict[str, int] = {}
    for e in events:
        t = (e.get("args") or {}).get("tenant")
        if isinstance(t, str):
            tenant_events[t] = tenant_events.get(t, 0) + 1
    # host activity mirrors tenant activity for the distributed serve
    # tier (runtime/distserve.py tags spawn/retire/death/late-epoch
    # instants with args.host): ranks which ingest host was implicated
    # when the process died, across every per-host shard of the bundle
    host_events: dict[str, int] = {}
    for e in events:
        h = (e.get("args") or {}).get("host")
        if isinstance(h, (int, str)) and not isinstance(h, bool):
            host_events[str(h)] = host_events.get(str(h), 0) + 1
    last = events[-1] if events else None
    return {
        "role": shard.get("role"),
        "pid": shard.get("pid"),
        "trigger": shard.get("trigger"),
        "stage_occupancy_pct": stage_occupancy(events),
        "fault_sites_fired": fault_sites,
        "tenant_events": tenant_events,
        "host_events": host_events,
        "last_event": (
            {"name": last.get("name"), "ph": last.get("ph")} if last else None
        ),
        "cursors": shard.get("cursors", {}),
    }


def _failing_stage(shards: list[dict]) -> str | None:
    """Best-evidence failing stage across the merged shards.

    The shard whose dump trigger is a failure (not a worker seal) rules;
    a stall prefers the dominant stall span of its final window
    (starved = the feed side stopped, backpressure = the device side
    wedged), otherwise the last event before the dump names the stage.
    """
    ranked = sorted(
        shards,
        key=lambda s: 0 if s.get("trigger") not in ("worker-exit",) else 1,
    )
    for shard in ranked:
        events = shard.get("ring_events", [])
        if not events:
            continue
        if shard.get("trigger") == "stall":
            occ = stage_occupancy(events)
            stalls = {
                k: v for k, v in occ.items()
                if k in ("ingest.starved", "ingest.backpressure")
            }
            if stalls:
                return max(stalls, key=stalls.get)
        for e in reversed(events):
            name = e.get("name", "")
            if name.startswith("fault."):
                continue  # the injected site is evidence, not a stage
            return name
    return None


def merge(
    blackbox_dir: str,
    *,
    trigger: str,
    error=None,
    exit_code: int | None = None,
    out_path: str | None = None,
) -> str:
    """Merge every per-PID shard into one ``postmortem.json`` bundle."""
    shards: list[dict] = []
    for path in sorted(glob.glob(os.path.join(blackbox_dir, "blackbox-*.json"))):
        try:
            with open(path, "r", encoding="utf-8") as f:
                shard = json.load(f)
        except (OSError, ValueError):
            continue  # a torn shard must not block the others' forensics
        if isinstance(shard, dict) and shard.get("kind") == "ra-blackbox-shard":
            shards.append(shard)
    per_shard = [_shard_analysis(s) for s in shards]
    fault_sites: dict[str, int] = {}
    tenant_events: dict[str, int] = {}
    host_events: dict[str, int] = {}
    dead_hosts: set[str] = set()
    retries: dict[str, dict] = {}
    queue_depths: dict[str, dict] = {}
    degraded: list[str] = []
    for shard, analysis in zip(shards, per_shard):
        for site, n in analysis["fault_sites_fired"].items():
            fault_sites[site] = fault_sites.get(site, 0) + n
        for t, n in analysis["tenant_events"].items():
            tenant_events[t] = tenant_events.get(t, 0) + n
        for h, n in analysis["host_events"].items():
            host_events[h] = host_events.get(h, 0) + n
        # the supervisor's cursor carries the authoritative dead set;
        # union across shards so a rank-0 dump and a surviving host's
        # seal agree on who died
        for h in (shard.get("cursors") or {}).get("dead_hosts", []) or []:
            dead_hosts.add(str(h))
        for site, c in (shard.get("retry") or {}).items():
            agg = retries.setdefault(
                site, {"attempts": 0, "recoveries": 0, "giveups": 0}
            )
            for k in agg:
                agg[k] += int(c.get(k, 0))
        samplers = shard.get("samplers") or {}
        ing = samplers.get("ingest")
        if isinstance(ing, dict):
            queue_depths[f"ingest@{shard.get('role')}"] = {
                "queue_depth": ing.get("queue_depth"),
                "prefetch_depth": ing.get("prefetch_depth"),
            }
        lst = samplers.get("listener")
        if isinstance(lst, dict):
            queue_depths[f"listener@{shard.get('role')}"] = {
                "depth": lst.get("depth"),
                "capacity": lst.get("capacity"),
                "dropped": lst.get("dropped"),
            }
        srv = samplers.get("serve")
        if isinstance(srv, dict) and srv.get("degraded_subsystems"):
            degraded.append(
                f"{srv['degraded_subsystems']} degraded subsystem(s)"
            )
    bundle = {
        "kind": "ra-postmortem",
        "version": 1,
        "created_unix": round(time.time(), 3),
        "trigger": trigger,
        "error": str(error)[:500] if error is not None else None,
        "error_type": type(error).__name__ if isinstance(
            error, BaseException
        ) else None,
        "exit_code": exit_code,
        "shards": shards,
        "analysis": {
            "dump_trigger": trigger,
            "failing_stage": _failing_stage(shards),
            "per_shard": per_shard,
            "fault_sites_fired": fault_sites,
            "tenant_events": tenant_events,
            "host_events": host_events,
            "dead_hosts": sorted(dead_hosts, key=lambda h: (len(h), h)),
            "retries": retries,
            "queue_depths": queue_depths,
            "degraded": degraded,
        },
    }
    os.makedirs(blackbox_dir, exist_ok=True)
    out_path = out_path or os.path.join(blackbox_dir, "postmortem.json")
    tmp = f"{out_path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(bundle, f, indent=1)
        os.replace(tmp, out_path)
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return out_path


def load_bundle(path: str) -> dict:
    """Read a postmortem bundle (a file, or a dir holding one)."""
    if os.path.isdir(path):
        path = os.path.join(path, "postmortem.json")
    with open(path, "r", encoding="utf-8") as f:
        bundle = json.load(f)
    if not isinstance(bundle, dict) or bundle.get("kind") != "ra-postmortem":
        raise AnalysisError(
            f"{path!r} is not a postmortem bundle (want kind=ra-postmortem; "
            "bundles are written beside the crash as "
            "BLACKBOX_DIR/postmortem.json)"
        )
    return bundle


def find_lineage(bundle_path: str) -> str | None:
    """Locate a ``lineage.jsonl`` adjacent to a postmortem bundle.

    The blackbox dir usually nests under — or sits beside — the serve
    dir that owns the ledger, so the bundle's own directory and its
    parent cover both layouts.
    """
    p = os.path.abspath(bundle_path)
    d = p if os.path.isdir(p) else os.path.dirname(p)
    for cand in (d, os.path.dirname(d)):
        lp = os.path.join(cand, "lineage.jsonl")
        if os.path.isfile(lp):
            return lp
    return None


def load_lineage(path: str) -> list[dict]:
    from .wal import LineageLog

    return LineageLog.read(path)


# ---------------------------------------------------------------------------
# Diagnosis: bundle + exit code -> ranked human-readable causes.
# ---------------------------------------------------------------------------


def diagnose(
    bundle: dict,
    exit_code: int | None = None,
    lineage: list[dict] | None = None,
) -> list[dict]:
    """Ranked diagnoses (most specific first) for one bundle.

    The first-response runbook for exit codes 3-8 (README "Exit codes"):
    each entry carries the suspected cause, the bundle evidence behind
    it, and the operator's next action.  When the serve dir's
    lineage.jsonl rides along (``lineage=``), the diagnosis also names
    the last fully-published window and the first missing or incomplete
    one — the precise re-ingest frontier after a crash.
    """
    from ..errors import EXIT_CODE_NAMES

    rc = exit_code if exit_code is not None else bundle.get("exit_code")
    a = bundle.get("analysis", {})
    out: list[dict] = []

    def add(cause: str, evidence: str, advice: str) -> None:
        out.append({
            "rank": len(out) + 1,
            "cause": cause,
            "evidence": evidence,
            "advice": advice,
        })

    sites = a.get("fault_sites_fired") or {}
    if sites:
        fired = ", ".join(f"{s} x{n}" for s, n in sorted(sites.items()))
        add(
            "an armed fault plan fired",
            f"fault site instant(s) on the ring: {fired}",
            "this failure was INJECTED (chaos drill); replay with the "
            "same --fault-plan spec to reproduce exactly",
        )
    dead_hosts = a.get("dead_hosts") or []
    if dead_hosts:
        named = ", ".join(f"host {h}" for h in dead_hosts)
        he = a.get("host_events") or {}
        hot = ", ".join(
            f"host {h} x{n}"
            for h, n in sorted(he.items(), key=lambda kv: -kv[1])[:4]
        )
        add(
            "a distributed-serve ingest host died mid-window "
            f"({named})",
            f"rank 0's cursor names dead host(s) {dead_hosts}"
            + (f"; host-tagged ring events: {hot}" if hot else ""),
            "windows overlapping the death carry a typed incomplete "
            "marker naming the host (host_died:<rank>) — their zero-hit "
            "rules are NOT deletion evidence; the host's WAL replays its "
            "tail on rejoin (--dist-respawn), and the per-host shard "
            "blackbox-*.json in this bundle holds its final ring",
        )
    stage = a.get("failing_stage")
    trigger = bundle.get("trigger")
    if rc == 3:
        add(
            "checkpoint corrupt (torn write / bit rot / CRC failure)",
            f"exit code 3 ({EXIT_CODE_NAMES.get(3)}); last stage: {stage}",
            "inspect the snapshot directory's manifest; delete the "
            "snapshot (or fix storage) and rerun — never resume from a "
            "corrupt snapshot",
        )
    elif rc == 4:
        add(
            "checkpoint/resume identity mismatch",
            f"exit code 4 ({EXIT_CODE_NAMES.get(4)})",
            "the snapshot was taken under a different ruleset, sketch "
            "geometry, or input; point --checkpoint-dir elsewhere or "
            "delete it to start fresh",
        )
    elif rc == 5:
        worker_shards = [
            s for s in a.get("per_shard", [])
            if s.get("role") not in (None, "main", "serve")
        ]
        add(
            "the feed tier failed (dead worker / corrupt wire / producer bug)",
            f"exit code 5 ({EXIT_CODE_NAMES.get(5)}); "
            f"{len(worker_shards)} worker shard(s) in the bundle; "
            f"last stage: {stage}",
            "check the worker shards' last events for the dying parse; "
            "an OOM-killed worker leaves NO shard of its own — the "
            "survivors' rings and the coordinator's FeedWorkerError "
            "name the dead slot",
        )
    elif rc == 6 or trigger == "stall":
        occ = {}
        for s in a.get("per_shard", []):
            for k, v in (s.get("stage_occupancy_pct") or {}).items():
                occ[k] = max(occ.get(k, 0.0), v)
        starved = occ.get("ingest.starved", 0.0)
        pressure = occ.get("ingest.backpressure", 0.0)
        if starved >= pressure and starved > 0:
            add(
                "pipeline stalled STARVED: the parse/feed side stopped "
                "delivering batches",
                f"ingest.starved occupied {starved}% of the final ring "
                f"window (backpressure {pressure}%)",
                "check the input source (hung NFS read, wedged feeder "
                "worker, dry listener); raise --stall-timeout only if "
                "the input is legitimately this slow",
            )
        elif pressure > 0:
            add(
                "pipeline stalled DEVICE-BOUND: the consumer stopped "
                "draining the queue",
                f"ingest.backpressure occupied {pressure}% of the final "
                f"ring window (starved {starved}%)",
                "check the device runtime (wedged collective, dead "
                "peer); the last step.dispatch on the ring names the "
                "program that never returned",
            )
        else:
            add(
                "watchdog stall with no stall spans on the ring",
                f"exit code 6 ({EXIT_CODE_NAMES.get(6)}); last stage: {stage}",
                "the stage that wedged emitted nothing — check the "
                "listener heartbeat gauges and the queue depths block",
            )
    elif rc == 7:
        add(
            "elastic re-formation budget exhausted (--max-reforms)",
            f"exit code 7 ({EXIT_CODE_NAMES.get(7)}); elastic.detect "
            "instants on the ring count the failures",
            "peers died more times than the budget allows; inspect the "
            "worker shards for the recurring death cause before raising "
            "--max-reforms",
        )
    elif rc == 8:
        fenced_by = next(
            (
                s.get("cursors", {}).get("fenced_by_term")
                for s in a.get("per_shard", [])
                if s.get("cursors", {}).get("fenced_by_term") is not None
            ),
            None,
        )
        term_txt = (
            f"fenced by term {fenced_by}" if fenced_by is not None
            else "renewals aged past the lease TTL"
        )
        add(
            f"stale distributed-serve supervisor {term_txt} — a "
            "successor won the publication lease",
            f"exit code 8 ({EXIT_CODE_NAMES.get(8)}); the error text "
            f"names the winning term and holder: {bundle.get('error')}",
            "this abort is the split-brain guard WORKING: the successor "
            "replays the per-host epoch spools and publishes every "
            "pending window bit-identically, so nothing is lost — do "
            "NOT restart this process against the same "
            "--dist-spool-dir while the winner is live; check "
            "lease.json there for the current holder",
        )
    elif trigger == "unhandled":
        add(
            "untyped crash (a programming error, not an operational fault)",
            f"trigger=unhandled, error={bundle.get('error_type')}: "
            f"{bundle.get('error')}",
            "this is a bug: file it with the bundle attached — the ring "
            "shows the last events before the crash",
        )
    if not out or (len(out) == 1 and sites):
        add(
            "typed analysis abort",
            f"trigger={trigger}, exit_code={rc}, "
            f"error={bundle.get('error_type')}: {bundle.get('error')}, "
            f"failing stage: {stage}",
            "the error text is the contract; the ring's final events "
            "and cursors show exactly what committed before the abort",
        )
    if a.get("tenant_events"):
        # multi-tenant serve: rank lanes by final-ring activity so the
        # operator knows WHOSE traffic/reload the process died under —
        # the cursors' last tenant names the in-flight lane exactly
        ranked_t = sorted(
            a["tenant_events"].items(), key=lambda kv: -kv[1]
        )[:5]
        cursor_tenant = next(
            (
                s.get("cursors", {}).get("tenant")
                for s in a.get("per_shard", [])
                if s.get("cursors", {}).get("tenant")
            ),
            None,
        )
        add(
            "multi-tenant service: per-tenant activity ranking",
            "final-ring events by tenant: "
            + ", ".join(f"{t} x{n}" for t, n in ranked_t)
            + (f"; cursor tenant: {cursor_tenant}" if cursor_tenant else ""),
            "the top-ranked tenant's window/reload was in flight at the "
            "dump; check its serve_dir/t/<name>/ reports and its "
            "last_reload_error in /health before blaming the shared tier",
        )
    if a.get("retries"):
        tot = sum(r.get("attempts", 0) for r in a["retries"].values())
        give = sum(r.get("giveups", 0) for r in a["retries"].values())
        if tot or give:
            add(
                "the retry plane was active before the failure",
                f"{tot} retry attempt(s), {give} giveup(s): "
                + ", ".join(sorted(a["retries"])),
                "a giveup means a transient seam exhausted its budget — "
                "the environment (disk/network/device) was failing "
                "repeatedly, not momentarily",
            )
    if a.get("degraded"):
        add(
            "non-core subsystems were already degraded",
            "; ".join(a["degraded"]),
            "the service was running in degraded mode before the "
            "failure — check /health history and the degraded "
            "subsystems' first errors",
        )
    es_win = [
        s["cursors"]["epochstore_window"]
        for s in a.get("per_shard", [])
        if s.get("cursors", {}).get("epochstore_window") is not None
    ]
    if es_win:
        es_levels = max(
            (
                s.get("cursors", {}).get("epochstore_levels") or 0
                for s in a.get("per_shard", [])
            ),
            default=0,
        )
        add(
            "durable epoch-store frontier at the dump",
            f"last spilled window: {max(es_win)}; "
            f"segment-tree levels: {es_levels}",
            "every window <= the frontier answers /report/range without "
            "replay; a frontier behind the lineage ledger's last "
            "complete window means the final rotation published but "
            "died before its spill — that window is recoverable from "
            "the WAL, not the store",
        )
    if lineage:
        from .report import lineage_frontier

        fr = lineage_frontier(lineage)
        last = fr.get("last_complete")
        first_bad = fr.get("first_incomplete")
        gaps = fr.get("gaps") or []
        if first_bad is None and gaps:
            first_bad = gaps[0]
        ev = (
            f"{fr.get('windows', 0)} lineage record(s); last complete "
            f"window: {last if last is not None else '-'}"
        )
        if first_bad is not None:
            ev += f"; first missing/incomplete window: {first_bad}"
        if gaps:
            ev += f"; gap window id(s): {gaps[:8]}"
        add(
            "publication frontier from the adjacent lineage ledger",
            ev,
            "every window <= the last complete id is durably published "
            "with a sealed lineage record; re-ingest (or failover "
            "replay) resumes from the first missing/incomplete window — "
            "its record (if any) names the hosts and WAL ranges that "
            "did NOT land",
        )
    return out


def render_diagnosis(bundle: dict, diagnoses: list[dict]) -> str:
    from ..errors import EXIT_CODE_NAMES

    rc = bundle.get("exit_code")
    head = [
        "== postmortem diagnosis ==",
        f"  trigger: {bundle.get('trigger')}   exit code: {rc}"
        + (f" ({EXIT_CODE_NAMES.get(rc)})" if rc in EXIT_CODE_NAMES else ""),
        f"  error: {bundle.get('error_type')}: {bundle.get('error')}",
        f"  shards: {len(bundle.get('shards', []))} "
        f"(roles: {', '.join(sorted({str(s.get('role')) for s in bundle.get('shards', [])})) or '-'})",
        f"  failing stage: {bundle.get('analysis', {}).get('failing_stage')}",
    ]
    for d in diagnoses:
        head.append(f"  [{d['rank']}] {d['cause']}")
        head.append(f"      evidence: {d['evidence']}")
        head.append(f"      next: {d['advice']}")
    return "\n".join(head)
