"""Pipelined ingest: bounded prefetch of device-ready batches.

The synchronous drivers interleave three stages on one thread — host
parse, host->device transfer, device step — and rely only on JAX's async
dispatch for overlap, so the end-to-end rate trends toward the SUM of the
stage times instead of their max (BENCH r5 measured pipeline_efficiency
0.35).  This module decouples the stages, MapReduce-input-split style
(SURVEY.md §2 L2): a background producer thread runs the source's batch
iterator (the parse stage — the native parser releases the GIL and fans
one batch across cores itself), optionally applies a ``pack`` transform
(flow coalescing when ``--coalesce`` is armed — the O(B) unique-row
hash pass, runtime/coalesce.py — then wire bit-packing and the async
sharded ``device_put``, so the queue holds device-ready batches and H2D
of chunk N+k overlaps the step of chunk N), and feeds a bounded queue
the driver's chunk loop consumes.

Correctness contract — COMMIT AT CONSUME, not at produce:

- Every queue item carries its batch plus the side effects its
  production implied: the source's cumulative parsed/skipped counters,
  the v6 rows staged while parsing it, and (elastic sources) the
  per-shard cursor snapshot.  The wrapper's public ``packer`` counters,
  ``take_v6`` buffer, and ``cursor_rows()`` only advance when the
  driver actually receives the batch — so a checkpoint taken at a chunk
  boundary covers exactly the committed lines, never lines the producer
  merely ran ahead on (the epoch-snapshot manifest records the last
  COMMITTED batch, not the last prefetched one).
- Batches flow through in source order (single producer, FIFO queue);
  with the inner iterator unchanged, every batch boundary — and
  therefore the full report, including per-chunk top-K candidates — is
  bit-identical to the synchronous driver.
- A producer exception is re-raised, typed, at the consumer's next
  pull; a consumer that stops early (crash simulation, ``close()``)
  signals the producer to stop so no thread is left blocked on a full
  queue.

The sources themselves already guarantee the donation/in-flight-mutation
constraint (every yielded array is freshly allocated — see
``_PackedSource._emit``), so producing ahead never mutates a buffer
under an in-flight async ``device_put``.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from ..errors import AnalysisError, IngestError, StallError
from . import faults, flightrec, obs
from .metrics import LatencyHistogram

_END = ("end", None)


class _Counters:
    """parsed/skipped counters advanced only as batches are committed."""

    def __init__(self):
        self.parsed = 0
        self.skipped = 0


class IngestStats:
    """Per-stage overlap accounting for one prefetched stream.

    ``produce_sec`` is time the producer spent inside the inner iterator
    plus the pack transform (the parse/H2D-issue stage);
    ``backpressure_sec`` is producer time blocked on a full queue (the
    device is the bottleneck); ``starved_sec`` is consumer time blocked
    on an empty queue (the parse is the bottleneck).  The report totals
    carry these so "parse-starved vs device-bound" is answerable from
    any run's JSON.
    """

    def __init__(self):
        self.produce_sec = 0.0
        self.backpressure_sec = 0.0
        self.starved_sec = 0.0
        self.batches = 0

    def to_dict(self) -> dict:
        return {
            "batches": self.batches,
            "produce_sec": round(self.produce_sec, 4),
            "backpressure_sec": round(self.backpressure_sec, 4),
            "starved_sec": round(self.starved_sec, 4),
        }


class _Pump:
    """One producer thread filling one bounded queue from one iterator."""

    def __init__(self, owner: "PrefetchingSource", it, *, with_v6: bool, pack):
        self.owner = owner
        self.q: queue.Queue = queue.Queue(maxsize=owner.depth)
        self.stop = threading.Event()
        self._it = it
        self._with_v6 = with_v6
        self._pack = pack
        self.thread = threading.Thread(
            target=self._produce, name="ra-ingest-producer", daemon=True
        )

    def _put(self, item) -> bool:
        """Enqueue with stop-responsiveness; False if the consumer left."""
        t0 = time.perf_counter()
        while not self.stop.is_set():
            try:
                self.q.put(item, timeout=0.1)
                t1 = time.perf_counter()
                self.owner.stats.backpressure_sec += t1 - t0
                if t1 - t0 >= obs.STALL_SPAN_MIN_SEC:
                    # producer blocked on a full queue: the device is
                    # the bottleneck for this interval
                    obs.complete("ingest.backpressure", t0, t1, cat="ingest")
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        owner = self.owner
        inner = owner._inner
        take_v6 = getattr(inner, "take_v6", None) if self._with_v6 else None
        cursor_rows = getattr(inner, "cursor_rows", None)
        pack = self._pack
        try:
            while True:
                t0 = time.perf_counter()
                # fault sites: a producer bug (typed at the consumer) and
                # a wedged producer (the consumer's stall watchdog fires)
                faults.fire("ingest.producer.raise")
                faults.fire("ingest.queue.stall", stop=self.stop)
                nxt = next(self._it, None)
                t_parsed = time.perf_counter()
                if nxt is None:
                    break
                batch, n_raw = nxt
                # side effects of producing THIS batch, captured now and
                # committed only when the consumer receives it
                v6 = take_v6() if take_v6 is not None else None
                parsed = inner.packer.parsed
                skipped = inner.packer.skipped
                cur = cursor_rows() if cursor_rows is not None else None
                obs.complete(
                    "ingest.produce", t0, t_parsed, cat="ingest",
                    args={"n_raw": n_raw},
                )
                if pack is not None and batch is not None:
                    batch = pack(batch)
                    # bit-pack + async sharded device_put (H2D issue)
                    obs.complete(
                        "ingest.pack", t_parsed, time.perf_counter(),
                        cat="ingest",
                    )
                owner.stats.produce_sec += time.perf_counter() - t0
                # t0 rides the item: the consumer records produce->commit
                # latency into the batch-e2e histogram at receipt
                if not self._put(
                    ("item", (batch, n_raw, parsed, skipped, v6, cur, t0))
                ):
                    return
        except BaseException as e:  # re-raised typed at the consumer
            self._put(("error", e))
            return
        self._put(_END)

    def _get_bounded(self):
        """Next queue item, bounded by the stall watchdog.

        Every received item resets the window, so a slow-but-advancing
        producer never trips it; a producer that is alive yet makes NO
        progress for ``stall_timeout`` seconds (hung I/O, deadlock, an
        injected ``ingest.queue.stall``) escalates to a typed StallError
        instead of wedging the driver forever.  A producer that died
        without its error/end sentinel (should be impossible — the
        sentinel put is unconditional) surfaces as IngestError.
        """
        timeout = self.owner.stall_timeout
        deadline = time.monotonic() + timeout
        while True:
            try:
                return self.q.get(timeout=0.2)
            except queue.Empty:
                if not self.thread.is_alive():
                    raise IngestError(
                        "ingest producer thread died without reporting"
                    ) from None
                if time.monotonic() > deadline:
                    raise StallError(
                        f"ingest producer made no progress in {timeout:.0f}s "
                        "(queue empty, producer alive); raise "
                        "--stall-timeout if the input is legitimately "
                        "this slow"
                    ) from None

    def consume(self):
        owner = self.owner
        self.thread.start()
        try:
            while True:
                t0 = time.perf_counter()
                tag, payload = self._get_bounded()
                t1 = time.perf_counter()
                owner.stats.starved_sec += t1 - t0
                if t1 - t0 >= obs.STALL_SPAN_MIN_SEC:
                    # consumer blocked on an empty queue: the parse is
                    # the bottleneck for this interval
                    obs.complete("ingest.starved", t0, t1, cat="ingest")
                if tag == "end":
                    return
                if tag == "error":
                    if isinstance(payload, AnalysisError) or not isinstance(
                        payload, Exception
                    ):
                        raise payload
                    # untyped producer failure: wrap so every failed run
                    # still exits with a typed AnalysisError (the chaos
                    # invariant); the original rides __cause__
                    raise IngestError(
                        f"ingest producer failed: "
                        f"{type(payload).__name__}: {payload}"
                    ) from payload
                batch, n_raw, parsed, skipped, v6, cur, t_prod = payload
                owner.packer.parsed = parsed
                owner.packer.skipped = skipped
                if v6 is not None and len(v6):
                    owner._staged6.append(v6)
                if cur is not None:
                    owner._cursor_rows = cur
                owner.stats.batches += 1
                # batch end-to-end latency, produce-start -> commit (the
                # moment the driver receives it): the ingest half of the
                # latency SLO plane (DESIGN §20)
                owner.latency.record(t1 - t_prod)
                # flight-recorder cursors: a crash dump names the last
                # COMMITTED batch (one dict update when armed)
                flightrec.cursor(
                    committed_batches=owner.stats.batches,
                    committed_parsed=parsed,
                )
                yield batch, n_raw
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        self.stop.set()
        deadline = time.monotonic() + 10.0
        # drain-and-join LOOP, not drain-then-join: a producer that was
        # mid-_put when we drained can enqueue one more item and block
        # again on a full depth-1 queue, so keep draining until it exits
        while self.thread.is_alive() and time.monotonic() < deadline:
            try:
                while True:
                    self.q.get_nowait()
            except queue.Empty:
                pass
            self.thread.join(timeout=0.1)
        if not self.thread.is_alive():
            # release the inner iterator's resources (feeder worker
            # pools, file handles) deterministically — an abandoned
            # generator would only run its finally at GC time, leaking
            # threads/processes past the consumer's exception
            close_it = getattr(self._it, "close", None)
            if close_it is not None:
                try:
                    close_it()
                except Exception:
                    pass  # teardown must not mask the consumer's error


class PrefetchingSource:
    """Wrap any stream source with a bounded background prefetch.

    Presents the same source protocol the drivers consume
    (``packer``/``set_counts``/``batches``/optional ``take_v6`` /
    ``batches6`` / ``cursor_rows`` / ``totals_patch`` / ``close``), so it
    drops in front of every tier: the native text parser (threads inside
    the GIL-releasing parse), the multi-worker feeders, the packed-array
    source, and the mmap'd wire reader (chunked reads happen in the
    producer thread).

    ``pack`` runs in the producer thread on every non-``None`` batch —
    the drivers pass the wire bit-pack + async sharded ``device_put``
    here, so queue items are device-ready and the H2D transfer of later
    chunks overlaps the current device step (double/triple buffering,
    sized by ``depth``; the default — 2 — lives in
    ``AnalysisConfig.prefetch_depth``, the single user surface).
    """

    def __init__(self, inner, depth: int, pack=None, stall_timeout: float | None = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._inner = inner
        self.depth = depth
        self._pack = pack
        #: watchdog bound on producer-to-consumer progress (see _get_bounded)
        self.stall_timeout = (
            stall_timeout if stall_timeout and stall_timeout > 0
            else faults.default_stall_timeout()
        )
        self.packer = _Counters()
        self.stats = IngestStats()
        #: produce->commit batch latency (log2 buckets, u64 counts —
        #: mergeable by addition); summarized into report totals.latency
        #: and every metrics snapshot
        self.latency = LatencyHistogram()
        self._staged6: list = []
        self._pumps: list[_Pump] = []
        self.yields_wire = getattr(inner, "yields_wire", False)
        #: weighted (coalesced) wire input: drivers key the fingerprint
        #: unit, padding shapes, grouped compaction, and the
        #: non-weight-linear-impl refusals off this — it must survive
        #: the wrap exactly like yields_wire
        self.yields_wire_weighted = getattr(
            inner, "yields_wire_weighted", False
        )
        self._cursor_rows = None
        # expose optional protocol members only when the inner source has
        # them: the drivers feature-detect with hasattr (e.g. a v6 step
        # is only built for sources exposing take_v6/batches6)
        if hasattr(inner, "take_v6"):
            self.take_v6 = self._take_v6
        if hasattr(inner, "batches6"):
            self.batches6 = self._batches6
        if hasattr(inner, "cursor_rows"):
            self._cursor_rows = inner.cursor_rows()
            self.cursor_rows = self._committed_cursor_rows
        if hasattr(inner, "totals_patch"):
            self.totals_patch = inner.totals_patch
        # live queue gauges for the metrics snapshotter (one None-check
        # when --metrics-out is unset); unregistered on close
        obs.register_sampler("ingest", self._sample_metrics)

    # -- delegated attributes -------------------------------------------
    @property
    def v6_digests(self):
        return self._inner.v6_digests

    @property
    def n4_rows(self):
        return self._inner.n4_rows

    def set_counts(self, parsed: int, skipped: int) -> None:
        self._inner.set_counts(parsed, skipped)
        self.packer.parsed, self.packer.skipped = parsed, skipped

    # -- committed side channels ----------------------------------------
    def _take_v6(self):
        staged = self._staged6
        self._staged6 = []
        if not staged:
            return []
        if len(staged) == 1:
            return staged[0]
        if isinstance(staged[0], np.ndarray):
            return np.concatenate(staged)
        out: list = []
        for rows in staged:
            out.extend(rows)
        return out

    def _committed_cursor_rows(self) -> np.ndarray:
        return self._cursor_rows

    # -- batch streams --------------------------------------------------
    def _pump_iter(self, it, with_v6: bool, pack):
        pump = _Pump(self, it, with_v6=with_v6, pack=pack)
        self._pumps.append(pump)
        return pump.consume()

    def batches(self, skip_lines: int, batch_size: int):
        return self._pump_iter(
            iter(self._inner.batches(skip_lines, batch_size)),
            with_v6=True,
            pack=self._pack,
        )

    def _batches6(self, skip_rows6: int, batch_size: int):
        # wire phase 2: v6 rows arrive as the batch itself, no side pull;
        # NO pack either — the drivers' run_chunk6 shards v6 batches
        # themselves (the v4 pack would double-shard them)
        return self._pump_iter(
            iter(self._inner.batches6(skip_rows6, batch_size)),
            with_v6=False,
            pack=None,
        )

    # -- lifecycle ------------------------------------------------------
    def ingest_stats(self) -> dict:
        return {"prefetch_depth": self.depth, **self.stats.to_dict()}

    def latency_summary(self) -> dict:
        """Report-facing ``totals.latency`` patch ({} before any batch)."""
        if self.latency.count == 0:
            return {}
        return {"batch_e2e": self.latency.summary()}

    def _sample_metrics(self) -> dict:
        """Live snapshot of the bounded queue + overlap accounting."""
        out = {
            "prefetch_depth": self.depth,
            "queue_depth": sum(p.q.qsize() for p in self._pumps),
            "batches": self.stats.batches,
            "produce_sec": round(self.stats.produce_sec, 3),
            "backpressure_sec": round(self.stats.backpressure_sec, 3),
            "starved_sec": round(self.stats.starved_sec, 3),
        }
        if self.latency.count:
            out.update(self.latency.gauges("latency_batch_e2e_"))
        return out

    def close(self) -> None:
        obs.unregister_sampler("ingest")
        for pump in self._pumps:
            pump.shutdown()
        inner_close = getattr(self._inner, "close", None)
        if inner_close is not None:
            inner_close()
