"""Self-validating benchmark timing: counts-closed step windows.

``jax.block_until_ready`` is not a reliable barrier on every PJRT plugin
(the remote-tunnel plugin used in development returns immediately for
shard_map outputs — round 2's headline benchmark reported 9x the VPU
roofline because of it).  Every timed step window in this repo therefore
closes with a host fetch of the count registers, which (a) cannot return
before every step in the window has executed, and (b) yields independent
evidence the work happened: each valid line adds exactly one count.

bench.py and bench_suite.py both use this helper so the sync discipline
cannot drift between them.
"""

from __future__ import annotations

import time


def timed_validated_steps(step, state, rules, feeds, valid_per_feed, iters):
    """Run ``iters`` steps over cycling resident feeds, timed and validated.

    Returns ``(state, dt, delta, expect)``: the new state, the wall time of
    the window (closed by a counts fetch), the measured count delta, and
    the expected delta (``sum of valid lines stepped``).  Callers must
    treat ``delta != expect`` as a measurement-integrity failure.
    """
    from ..models import pipeline

    base = pipeline.counts_total(state)
    t0 = time.perf_counter()
    for i in range(iters):
        state, _out = step(state, rules, feeds[i % len(feeds)])
    total = pipeline.counts_total(state)  # sync + evidence, inside the window
    dt = time.perf_counter() - t0
    expect = sum(valid_per_feed[i % len(valid_per_feed)] for i in range(iters))
    return state, dt, total - base, expect
