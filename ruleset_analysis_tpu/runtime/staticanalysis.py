"""Static ruleset analysis: which rules can NEVER get a hit (ISSUE 12).

The live pipeline answers "which rules got no hits" — a traffic-dependent
fact.  First-match semantics (SURVEY §5: configuration order + implicit
deny + overlapping rules) also define a purely static question: a rule
whose entire match space is claimed by earlier rules of its ACL is
*provably dead* — no packet, in any traffic mix, can ever hit it.  This
module computes per-rule verdicts over the packed ``[R, RULE_COLS]``
tensor and joins them with live hit evidence in the reports:

  unused + dead       -> safe to delete (static proof, not absence of
                         traffic)
  unused + reachable  -> traffic-dependent (keep watching)
  hit    + dead       -> analyzer contradiction -> typed
                         :class:`~..errors.AnalyzerContradiction`,
                         never silent

Verdict lattice (per configured rule):

  ``redundant``        an earlier single rule covers every ACE with the
                       SAME action (exact: per-pair interval subset)
  ``conflict``         covered by earlier single rules with a DIFFERENT
                       action (the rule is dead AND deleting it is a
                       semantic no-op only because it never fired)
  ``shadowed``         dead, but not by one same/different-action rule:
                       mixed/unknown actions, or a UNION of earlier
                       rules covers it (certified by witness
                       exhaustion, below)
  ``partially-masked`` earlier rules steal part of its space; a
                       concrete witness packet (or an exhausted budget)
                       says whether it is still reachable
  ``reachable``        no earlier rule overlaps it at all

Exactness contract: single-rule coverage is decided exactly from the
pairwise interval relations (ops/overlap.py, the device-tiled
``ra.overlap`` kernel).  UNION coverage is *certified, not decided*: the
corner-point grid built from ``{lo}`` and masking-row ``{hi+1}``
endpoints provably contains a witness packet iff one exists (minimal-
uncovered-point argument, DESIGN §17), and every candidate is run
through the production ``first_match_rows`` kernel — a hit on the rule
is a concrete, device-checked reachability witness.  A rule is only
ever marked dead with (a) an exact single-rule cover or (b) a COMPLETE
witness-exhaustion record; when the grid exceeds the witness budget the
verdict honestly stays ``partially-masked`` with ``certified: false``.

Failure model: the tile loop threads the ``analyze.tile`` fault site;
an analysis that fails at ANY point raises typed — the returned
:class:`StaticAnalysis` is always a COMPLETE verdict set, never a
partial table presented as complete.

Incremental re-analysis (serve hot reload): verdicts depend only on an
ACL's own ordered rows + actions, so each ACL carries a content
signature; a reload re-tiles only ACLs whose signature changed and
remaps the untouched ACLs' verdicts positionally (the migration-map
idea applied to verdicts).
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import time

import numpy as np

from ..errors import AnalysisError, AnalyzerContradiction
from ..hostside import pack as pack_mod
from ..hostside.pack import _RANGE_COLS, NO_ACL, R_ACL, R_KEY, RULE_BLOCK
from . import faults, obs

REACHABLE = "reachable"
SHADOWED = "shadowed"
REDUNDANT = "redundant"
CONFLICT = "conflict"
PARTIAL = "partially-masked"

#: verdicts that assert "this rule can never get a hit"
DEAD_VERDICTS = frozenset({SHADOWED, REDUNDANT, CONFLICT})

#: evidence classes the unused-rule report joins verdicts into
CLASS_SAFE = "safe_to_delete"
CLASS_TRAFFIC = "traffic_dependent"
CLASS_UNDECIDED = "undecided"

#: per-rule cap on witness-grid enumeration (overridable per call); the
#: grid is exact when fully enumerated, so the budget only bounds WORK —
#: past it a verdict stays partially-masked/uncertified, never dead
DEFAULT_WITNESS_BUDGET = 4096

#: fixed certifier batch: candidates pad to this so ONE first_match jit
#: compile (per ruleset shape) serves every rule's witness run
_CAND_CHUNK = 2048

#: derived from the pack layer's canonical range-column table (shared
#: with ops/overlap.py) — the witness grids and the relation predicates
#: must agree on the field set or exhaustion proofs become unsound
_FIELDS = tuple((lo, hi) for lo, hi, _name in _RANGE_COLS)


@dataclasses.dataclass
class RuleVerdict:
    """One configured rule's static verdict + its evidence."""

    key_id: int
    verdict: str
    basis: str  # single-cover | witness-exhaustion | witness | disjoint | ...
    certified: bool  # exact proof vs budget-truncated evidence
    cover_key: int | None = None  # exact single-rule cover (earliest)
    witness: list[int] | None = None  # [proto, src, sport, dst, dport]
    witnesses_checked: int = 0
    witness_grid: int = 0  # full corner-grid size (0 = no grid needed)

    @property
    def dead(self) -> bool:
        return self.verdict in DEAD_VERDICTS

    def to_obj(self, packed: pack_mod.PackedRuleset) -> dict:
        m = packed.key_meta[self.key_id]
        out = {
            "rule": f"{m.firewall} {m.acl} {m.index}",
            "key_id": self.key_id,
            "verdict": self.verdict,
            "basis": self.basis,
            "certified": self.certified,
        }
        if self.cover_key is not None:
            cm = packed.key_meta[self.cover_key]
            out["cover"] = f"{cm.firewall} {cm.acl} {cm.index}"
        if self.witness is not None:
            out["witness"] = list(self.witness)
        if self.witness_grid:
            # the witness-exhaustion record: how big the exact corner
            # grid was and how much of it was actually device-checked
            out["witness_grid"] = self.witness_grid
            out["witnesses_checked"] = self.witnesses_checked
        return out


@dataclasses.dataclass
class StaticAnalysis:
    """A COMPLETE verdict set over one packed ruleset."""

    verdicts: dict[int, RuleVerdict]  # key_id -> verdict (configured rules)
    meta: dict
    #: (firewall, acl) -> (signature, ordered key ids): the incremental
    #: reuse index a later :func:`analyze_ruleset` call consumes
    acl_index: dict[tuple[str, str], tuple[bytes, list[int]]]

    def dead_keys(self) -> set[int]:
        return {k for k, v in self.verdicts.items() if v.dead}

    def to_obj(self, packed: pack_mod.PackedRuleset) -> dict:
        return {
            "meta": dict(self.meta),
            "verdicts": [
                self.verdicts[k].to_obj(packed) for k in sorted(self.verdicts)
            ],
        }


# ---------------------------------------------------------------------------
# Device certifier: candidate packets through the production match kernel.
# ---------------------------------------------------------------------------


class _Certifier:
    """Runs candidate packets through ``first_match_rows`` (ops/match.py).

    The analyzer never upgrades a verdict to "dead" on its own interval
    algebra alone for union coverage — reachability witnesses come from
    the same compiled kernel the live pipeline counts hits with, so a
    witness IS a packet the production path would attribute to the rule.
    Candidates pad to a fixed chunk so one compile serves the whole run.
    """

    def __init__(self, packed: pack_mod.PackedRuleset, chunk: int = _CAND_CHUNK):
        import jax.numpy as jnp

        from ..models.pipeline import pad_rules

        self.chunk = chunk
        # the production padding (ship_ruleset uses the same helper):
        # one definition of the block-multiple invariant the kernel needs
        self._rules = jnp.asarray(pad_rules(packed.rules, RULE_BLOCK))
        self._deny = jnp.asarray(packed.deny_key)

    def match_keys(self, tuples: np.ndarray) -> np.ndarray:
        """``[N, 6] (acl, proto, src, sport, dst, dport)`` -> key per row."""
        import jax.numpy as jnp

        from ..ops import match as match_mod

        n = tuples.shape[0]
        out = np.empty(n, dtype=np.uint32)
        for c0 in range(0, n, self.chunk):
            c1 = min(c0 + self.chunk, n)
            # the tail (often a rule's whole tiny corner grid) pads to
            # the next power of two, not the full chunk: at most
            # log2(chunk) compiled shapes per process, and a 2-point
            # grid stops paying a 2048-row dispatch of padding
            cap = 64
            while cap < c1 - c0:
                cap <<= 1
            block = np.zeros((min(cap, self.chunk), 6), dtype=np.uint32)
            block[: c1 - c0] = tuples[c0:c1]
            cols = {
                name: jnp.asarray(block[:, i])
                for i, name in enumerate(
                    ("acl", "proto", "src", "sport", "dst", "dport")
                )
            }
            keys = match_mod.match_keys(cols, self._rules, self._deny)
            out[c0:c1] = np.asarray(keys)[: c1 - c0]
        return out


# ---------------------------------------------------------------------------
# Witness-grid candidate generation (the corner-point construction).
# ---------------------------------------------------------------------------


def _grid_coords(
    sub: np.ndarray, a: int, maskers: np.ndarray
) -> list[list[int]]:
    """Per-field corner candidates for row ``a`` against ``maskers``.

    ``{lo_a}`` plus every masking row's ``hi+1`` that lands inside
    ``[lo_a, hi_a]``.  The cross-product grid contains an uncovered
    point iff row a's box minus the maskers' union is non-empty
    (minimal-uncovered-point argument; DESIGN §17), so full enumeration
    DECIDES union coverage — the budget only truncates work, never
    soundness of a dead verdict.
    """
    coords: list[list[int]] = []
    for lo, hi in _FIELDS:
        lo_a, hi_a = int(sub[a, lo]), int(sub[a, hi])
        vals = {lo_a}
        for b in maskers:
            v = int(sub[b, hi]) + 1
            if lo_a <= v <= hi_a:
                vals.add(v)
        coords.append(sorted(vals))
    return coords


def _grid_size(coords: list[list[int]]) -> int:
    n = 1
    for c in coords:
        n *= len(c)
    return n


def _enumerate_grid(coords: list[list[int]], cap: int) -> np.ndarray:
    """First ``cap`` grid points in lexicographic order, ``[n, 5]``."""
    out = []
    for p in itertools.product(*coords):
        out.append(p)
        if len(out) >= cap:
            break
    return np.asarray(out, dtype=np.uint32).reshape(-1, 5)


# ---------------------------------------------------------------------------
# Per-ACL signatures (incremental re-analysis on hot reload).
# ---------------------------------------------------------------------------


def _acl_signature(
    sub: np.ndarray, local_keys: np.ndarray, actions: list[int], v6_local: list[int]
) -> bytes:
    """Content signature of one ACL's analysis input.

    Covers exactly what verdicts depend on: the ordered interval rows
    (ACL gid column zeroed — renumbering gids must not fake a change),
    each row's key as a LOCAL ordinal (global renumbering preserves
    verdicts), per-key actions, and which local keys carry v6 rows.
    """
    img = sub.copy()
    img[:, R_ACL] = 0
    img[:, R_KEY] = local_keys
    h = hashlib.sha256(img.tobytes())
    h.update(np.asarray(actions, dtype=np.int64).tobytes())
    h.update(np.asarray(sorted(v6_local), dtype=np.int64).tobytes())
    return h.digest()


# ---------------------------------------------------------------------------
# The analyzer.
# ---------------------------------------------------------------------------


def analyze_ruleset(
    packed: pack_mod.PackedRuleset,
    *,
    tile: int | None = None,
    witness_budget: int = DEFAULT_WITNESS_BUDGET,
    devices: list | None = None,
    reuse: StaticAnalysis | None = None,
) -> StaticAnalysis:
    """Full static analysis of a packed ruleset -> per-rule verdicts.

    O(Ra²) pair tiles per ACL on device (``ra.overlap``), then host
    aggregation + the device-certified witness pass.  ``reuse`` (a prior
    run's result, e.g. across a hot reload) skips ACLs whose content
    signature is unchanged, remapping their verdicts to the new key ids.
    Raises typed on any failure — callers never see a partial table.
    """
    from ..ops import overlap as overlap_mod

    if witness_budget < 1:
        raise AnalysisError(
            f"witness budget must be >= 1, got {witness_budget}"
        )
    tile = tile or overlap_mod.PAIR_TILE
    t0 = time.monotonic()
    rules = packed.rules
    real = rules[:, R_ACL] != NO_ACL
    pack_mod.validate_rule_ranges(rules[real])

    # keys carrying v6 rows: their v4-side analysis can bound but never
    # kill them (a v6 packet could still reach the rule; the v4 kernel
    # cannot certify that half)
    v6_keys: set[int] = set()
    if packed.has_v6:
        v6_keys = set(
            int(k) for k in packed.rules6[
                packed.rules6[:, pack_mod.R6_ACL] != NO_ACL, pack_mod.R6_KEY
            ]
        )

    gid_name = {gid: name for name, gid in packed.acl_gid.items()}
    reuse_index = dict(reuse.acl_index) if reuse is not None else {}
    reuse_verdicts = reuse.verdicts if reuse is not None else {}

    certifier: _Certifier | None = None
    verdicts: dict[int, RuleVerdict] = {}
    acl_index: dict[tuple[str, str], tuple[bytes, list[int]]] = {}
    analyzed_acls = 0
    reused_acls = 0
    tiles_run = 0
    witnesses_run = 0

    row_key = rules[:, R_KEY].astype(np.int64)
    row_acl = rules[:, R_ACL].astype(np.int64)
    # every key of each ACL (a pure-v6 rule has no v4 rows but still
    # needs a verdict); key ids ascend in config order by construction
    keys_by_name: dict[tuple[str, str], list[int]] = {}
    for kid, m in enumerate(packed.key_meta):
        if not m.implicit_deny:
            keys_by_name.setdefault((m.firewall, m.acl), []).append(kid)
    for gid in range(packed.n_acls):
        name = gid_name.get(gid)
        rows_idx = np.nonzero(real & (row_acl == gid))[0]
        sub = np.ascontiguousarray(rules[rows_idx])
        keys = row_key[rows_idx]  # global key ids, config order
        acl_keys = keys_by_name.get(name, [])
        if not acl_keys:
            continue
        base = acl_keys[0]
        local_keys = keys - base
        actions = [packed.key_meta[k].action for k in acl_keys]
        v6_local = [k - base for k in acl_keys if k in v6_keys]
        sig = _acl_signature(sub, local_keys, actions, v6_local)
        acl_index[name] = (sig, acl_keys)

        prior = reuse_index.get(name)
        if prior is not None and prior[0] == sig and len(prior[1]) == len(acl_keys):
            # unchanged ACL: remap the prior verdicts positionally (the
            # signature pins rows, local key ordinals, actions, and the
            # v6 set, so the verdicts are identical by construction)
            old_to_new = dict(zip(prior[1], acl_keys))
            for old_kid in prior[1]:
                ov = reuse_verdicts[old_kid]
                verdicts[old_to_new[old_kid]] = dataclasses.replace(
                    ov,
                    key_id=old_to_new[old_kid],
                    cover_key=(
                        old_to_new.get(ov.cover_key)
                        if ov.cover_key is not None
                        else None
                    ),
                )
            reused_acls += 1
            continue
        analyzed_acls += 1

        # --- pair relations, device tiles --------------------------------
        def on_tile(i0, j0, _gid=gid):
            nonlocal tiles_run
            tiles_run += 1
            # chaos seam: a tile failing mid-grid must abort the whole
            # analysis typed — never ship the tiles computed so far
            faults.fire("analyze.tile")

        # lower_only: slab rows are key-ascending, so tiles strictly
        # above the diagonal can never survive the earlier-key mask —
        # the tile grid halves with bit-identical verdicts
        covered, ovl = overlap_mod.pair_relations(
            sub, tile=tile, devices=devices, on_tile=on_tile,
            lower_only=True,
        )
        # earlier-rule mask: rows of EARLIER keys only (rows of the same
        # key attribute hits to the rule itself, so they never mask it)
        earlier = keys[None, :] < keys[:, None]  # [a, b]: b's key earlier
        cov_e = covered & earlier
        ovl_e = ovl & earlier

        for pos, kid in enumerate(acl_keys):
            rows_of_key = np.nonzero(keys == kid)[0]
            if rows_of_key.size == 0:
                # pure-v6 rule: nothing the v4 plane can say
                verdicts[kid] = RuleVerdict(
                    key_id=kid, verdict=PARTIAL, basis="v6-rows-unanalyzed",
                    certified=False,
                )
                continue
            v = _verdict_for_key(packed, keys, kid, rows_of_key, cov_e, ovl_e)
            if v is None:
                # witness pass needed: build lazily, batch per rule
                if certifier is None:
                    certifier = _Certifier(packed)
                v, n_checked = _witness_verdict(
                    packed, sub, keys, kid, rows_of_key, cov_e, ovl_e,
                    witness_budget, certifier, gid,
                )
                witnesses_run += n_checked
            if kid in v6_keys and v.dead:
                # v4-dead but v6 rows exist: the rule may still match v6
                # traffic — never claim dead from the v4 plane alone
                v = dataclasses.replace(
                    v, verdict=PARTIAL, basis="v4-dead-v6-unanalyzed",
                    certified=False,
                )
            verdicts[kid] = v

    counts: dict[str, int] = {}
    for v in verdicts.values():
        counts[v.verdict] = counts.get(v.verdict, 0) + 1
    meta = {
        "n_rules": packed.n_rules,
        "n_acls": packed.n_acls,
        "n_rows": int(real.sum()),
        "tile": tile,
        "witness_budget": witness_budget,
        "tiles_run": tiles_run,
        "witnesses_checked": witnesses_run,
        "analyzed_acls": analyzed_acls,
        "reused_acls": reused_acls,
        "duration_sec": round(time.monotonic() - t0, 4),
        "verdict_counts": counts,
        "dead": sum(counts.get(k, 0) for k in DEAD_VERDICTS),
        # a StaticAnalysis object only exists COMPLETE: any failure
        # raises before construction (the analyze.tile invariant)
        "complete": True,
    }
    return StaticAnalysis(verdicts=verdicts, meta=meta, acl_index=acl_index)


def _verdict_for_key(
    packed, keys, kid, rows_of_key, cov_e, ovl_e
) -> RuleVerdict | None:
    """Exact verdicts decidable from pair relations alone (None = needs
    the witness pass)."""
    covered_rows = cov_e[rows_of_key].any(axis=1)
    if covered_rows.all():
        # every ACE exactly covered by one earlier rule: dead, with the
        # redundant/conflict/shadowed split read off the cover actions
        my_action = packed.key_meta[kid].action
        cover_keys = []
        for a in rows_of_key:
            b = int(np.nonzero(cov_e[a])[0][0])  # earliest covering row
            cover_keys.append(int(keys[b]))
        cover_actions = {packed.key_meta[c].action for c in cover_keys}
        if my_action >= 0 and cover_actions == {my_action}:
            verdict = REDUNDANT
        elif my_action >= 0 and -1 not in cover_actions and my_action not in cover_actions:
            verdict = CONFLICT
        else:
            verdict = SHADOWED  # mixed or unknown actions: still dead
        return RuleVerdict(
            key_id=kid, verdict=verdict, basis="single-cover",
            certified=True, cover_key=cover_keys[0],
        )
    if not ovl_e[rows_of_key].any():
        return RuleVerdict(
            key_id=kid, verdict=REACHABLE, basis="disjoint", certified=True
        )
    return None


def _witness_verdict(
    packed, sub, keys, kid, rows_of_key, cov_e, ovl_e, witness_budget,
    certifier, gid,
) -> tuple[RuleVerdict, int]:
    """Union-coverage certification for one rule (the witness pass)."""
    grids: list[np.ndarray] = []
    grid_total = 0
    budget_left = witness_budget
    for a in rows_of_key:
        if cov_e[a].any():
            continue  # this ACE is exactly covered: no witness there
        maskers = np.nonzero(ovl_e[a])[0]
        coords = _grid_coords(sub, a, maskers)
        grid_total += _grid_size(coords)
        if budget_left > 0:
            g = _enumerate_grid(coords, budget_left)
            budget_left -= g.shape[0]
            grids.append(g)
    cand = (
        np.concatenate(grids, axis=0)
        if grids
        else np.zeros((0, 5), dtype=np.uint32)
    )
    tuples = np.zeros((cand.shape[0], 6), dtype=np.uint32)
    tuples[:, 0] = gid
    tuples[:, 1:] = cand
    matched = certifier.match_keys(tuples) if cand.shape[0] else np.zeros(0)
    hit = np.nonzero(matched == kid)[0]
    if hit.size:
        w = [int(x) for x in cand[int(hit[0])]]
        return (
            RuleVerdict(
                key_id=kid, verdict=PARTIAL, basis="witness", certified=True,
                witness=w, witnesses_checked=int(cand.shape[0]),
                witness_grid=grid_total,
            ),
            int(cand.shape[0]),
        )
    if grid_total <= witness_budget:
        # full corner grid enumerated, zero witnesses: the union of
        # earlier rules covers every ACE — dead, with the exhaustion
        # record as the proof object
        return (
            RuleVerdict(
                key_id=kid, verdict=SHADOWED, basis="witness-exhaustion",
                certified=True, witnesses_checked=int(cand.shape[0]),
                witness_grid=grid_total,
            ),
            int(cand.shape[0]),
        )
    # budget truncated the grid and no witness surfaced: honestly
    # undecided — NOT dead
    return (
        RuleVerdict(
            key_id=kid, verdict=PARTIAL, basis="witness-budget",
            certified=False, witnesses_checked=int(cand.shape[0]),
            witness_grid=grid_total,
        ),
        int(cand.shape[0]),
    )


# ---------------------------------------------------------------------------
# Report join: verdicts x live hit evidence.
# ---------------------------------------------------------------------------


def unused_class(verdict: dict) -> str:
    """Evidence class of an unused rule given its verdict object."""
    if verdict["verdict"] in DEAD_VERDICTS:
        return CLASS_SAFE
    if verdict["certified"] or verdict["verdict"] == REACHABLE:
        return CLASS_TRAFFIC
    return CLASS_UNDECIDED


def attach_static_obj(obj: dict, sa_obj: dict, *, strict: bool = True) -> dict:
    """Join a static-analysis object into a report JSON object, in place.

    Adds per-rule ``verdict``/``verdict_basis``/``verdict_certified``
    fields, a ``totals.static`` block (analysis meta + the unused-rule
    evidence classes), and enforces the contradiction invariant: a rule
    with live hits and a dead verdict raises
    :class:`~..errors.AnalyzerContradiction` when ``strict`` (reports
    whose counters belong entirely to the analyzed ruleset), else is
    recorded in ``totals.static.contradictions`` — visible either way,
    silent never.  ``strict=False`` is for reports whose counters span a
    ruleset reload (migrated windows, cumulative/merged views): hits
    earned under an OLD ruleset legitimately coexist with a dead verdict
    under the new one.
    """
    by_key = {v["key_id"]: v for v in sa_obj["verdicts"]}
    classes: dict[str, list[str]] = {
        CLASS_SAFE: [], CLASS_TRAFFIC: [], CLASS_UNDECIDED: []
    }
    contradictions: list[dict] = []
    for e in obj["per_rule"]:
        v = by_key.get(e["key_id"])
        if v is None:
            continue  # implicit-deny keys carry no verdict
        e["verdict"] = v["verdict"]
        e["verdict_basis"] = v["basis"]
        e["verdict_certified"] = v["certified"]
        rule = f"{e['firewall']} {e['acl']} {e['index']}"
        if e["hits"] == 0:
            classes[unused_class(v)].append(rule)
        elif v["verdict"] in DEAD_VERDICTS:
            contradictions.append(
                {"rule": rule, "hits": e["hits"], "verdict": v["verdict"]}
            )
    totals = obj["totals"]
    totals["static"] = {
        "meta": dict(sa_obj["meta"]),
        "unused_classes": classes,
    }
    if contradictions:
        if strict:
            first = contradictions[0]
            raise AnalyzerContradiction(
                f"rule {first['rule']} has {first['hits']} live hit(s) but "
                f"a certified '{first['verdict']}' (dead) verdict "
                f"({len(contradictions)} contradicting rule(s) total); the "
                "analyzer or the counters are wrong — refusing to publish "
                "the contradiction as a report"
            )
        totals["static"]["contradictions"] = contradictions
    return obj


def attach_static(rep, packed: pack_mod.PackedRuleset, sa: StaticAnalysis,
                  *, strict: bool = True):
    """:func:`attach_static_obj` for a :class:`~.report.Report` object."""
    attach_static_obj(
        {"per_rule": rep.per_rule, "totals": rep.totals},
        sa.to_obj(packed),
        strict=strict,
    )
    return rep


# ---------------------------------------------------------------------------
# CLI rendering (the `analyze` subcommand's text view).
# ---------------------------------------------------------------------------


def render_text(packed: pack_mod.PackedRuleset, sa_obj: dict) -> str:
    m = sa_obj["meta"]
    out = [
        f"# static analysis: {m['n_rules']} rules, {m['n_acls']} ACLs, "
        f"{m['n_rows']} ACE rows; {m['tiles_run']} pair tiles, "
        f"{m['witnesses_checked']} witness packets device-checked "
        f"({m['duration_sec']}s)"
    ]
    counts = ", ".join(
        f"{k}={v}" for k, v in sorted(m["verdict_counts"].items())
    )
    out.append(f"# verdicts: {counts}  (provably dead: {m['dead']})")
    by_acl: dict[str, list[dict]] = {}
    for v in sa_obj["verdicts"]:
        fw, acl, _ = v["rule"].rsplit(" ", 2)
        by_acl.setdefault(f"{fw} / {acl}", []).append(v)
    for name, vs in by_acl.items():
        out.append(f"\n== {name} ==")
        for v in vs:
            idx = v["rule"].rsplit(" ", 1)[1]
            extra = ""
            if v.get("cover"):
                extra = f"  covered by rule {v['cover'].rsplit(' ', 1)[1]}"
            elif v.get("witness"):
                extra = f"  witness={v['witness']}"
            elif v.get("witness_grid"):
                extra = (
                    f"  grid={v['witness_grid']} "
                    f"checked={v['witnesses_checked']}"
                )
            cert = "" if v["certified"] else "  [uncertified]"
            text = packed.key_meta[v["key_id"]].text
            out.append(
                f"  rule {idx:>4}: {v['verdict']:<16} ({v['basis']})"
                f"{extra}{cert}  | {text}"
            )
    return "\n".join(out)
