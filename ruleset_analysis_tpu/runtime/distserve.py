"""Multi-host distributed serve: per-host ingest tiers + cross-host
register merge over the hybrid mesh's host (dcn) axis (DESIGN §22).

``serve --distributed`` splits the always-on service into two planes:

- **Host ingest workers** (:class:`HostServeDriver`, one per host): a
  full single-host serve loop — listeners, LineQueue, feeder, WAL
  spool, flight-recorder shard, device mesh — ingesting its own traffic
  slice into host-local register planes.  Publication is stripped to
  nothing: at every window rotation the closed epoch (register arrays +
  tracker tables + accounting meta + WAL cursor) ships to rank 0 as one
  CRC-framed payload (parallel/distributed.py::pack_epoch_payload).

- **Rank-0 merge + publication** (:class:`DistServeDriver`): collects
  each window's per-host epochs and merges them under the ``_merge_tail``
  laws (add64 for exact counts, add mod 2^32 for CMS planes, max for
  HLL) — the same associative laws the in-mesh ``("dcn", data)``
  collective reduces over, realized host-side so a dead host degrades
  the service instead of poisoning a pending collective.  The merged
  window is bit-identical to a single-host replay of the union of all
  hosts' delivered lines (registers AND report body, candidates
  included — pinned by tests/test_distserve.py), and rank 0 owns every
  publication surface: window/cumulative/diff JSON, merged views, the
  HTTP endpoint, and the merged-ring checkpoint.

Ordering + liveness: merged windows publish strictly in window-id
order.  Window ``w`` publishes when every host expected at ``w`` has
submitted it; a host marked dead completes the window immediately
(named in the typed ``WindowIncomplete`` marker — never a hang, never
a silent zero-hit), and a live-but-silent host is waited on for
``merge_timeout_sec`` past the window's first arrival, then named as
missing.  A host's late epoch for an already-published window is
dropped with explicit accounting (``late_epochs`` in /health), never
silently merged or silently discarded.

Elasticity: the checkpoint fingerprint pins the host-tier ladder
MAXIMUM (``DistServeConfig.ladder_max``), not the live host count —
the merged registers are world-size-independent, so a checkpoint taken
at 2 hosts resumes at 3 (and vice versa).  With ``--autoscale`` the
policy engine is promoted to a host-tier actuator: scale-out spawns a
fresh host joining at the merge frontier; scale-in retires the
highest-rank host, which stops ingress, drains its queue into one
final window marked ``retired``, and leaves cleanly — never a silent
drop.  An unexpectedly dead host (SIGKILL, OOM) is respawned when
``--dist-respawn`` is set; the replacement replays its predecessor's
WAL tail past the last merged seq.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import struct
import threading
import time
import zlib

import numpy as np

from ..config import (
    AnalysisConfig, AutoscaleConfig, DistServeConfig, ServeConfig,
)
from ..errors import AnalysisError, SupervisorFenced, WalQuarantine
from ..hostside import pack as pack_mod
from ..hostside.listener import offset_listen_spec
from ..models import pipeline
from ..ops.topk import TopKTracker
from ..parallel.distributed import pack_epoch_payload, unpack_epoch_payload
from . import checkpoint as ckpt
from . import epochstore, faults, flightrec, obs, retrypolicy
from .lease import EpochSpool, SupervisorLease
from .autoscale import PolicyEngine, host_ladder, render_prom_labeled
from .metrics import LatencyHistogram, build_info, render_build_info_prom
from .serve import (
    ServeDriver, WindowEpoch, WindowRing, _make_http_server,
    _merge_quarantine, merge_register_arrays, zero_arrays,
)
from .wal import LineageLog
from .report import seal_lineage

# ---------------------------------------------------------------------------
# Host-tier control frames: one length-prefixed frame = u32 LE body
# length + 1 kind byte + body.  Worker -> rank 0: H(ello, JSON),
# E(poch, pack_epoch_payload bytes), F (an epoch draining out of the
# partition backlog at heal — same body as E, lineage path stamp
# differs), G(auges, JSON), B(ye, JSON).
# Rank 0 -> worker: R(etire), S(top).  Thread-mode workers skip the
# socket but run the SAME frames through the same dispatch, so the wire
# discipline is exercised in-tier, not only in the slow process tests.
# ---------------------------------------------------------------------------

#: frame size ceiling: a register epoch is MBs, never GBs — anything
#: larger is a corrupt length prefix, refused before allocation
_FRAME_MAX = 1 << 31


def _send_frame(sock: socket.socket, kind: bytes, body: bytes) -> None:
    sock.sendall(struct.pack("<I", len(body) + 1) + kind + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> tuple[bytes, bytes] | None:
    """One frame, or None on clean EOF; typed error on a torn frame."""
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = struct.unpack("<I", hdr)
    if not 1 <= n <= _FRAME_MAX:
        raise AnalysisError(f"host-tier frame length {n} out of range")
    body = _recv_exact(sock, n)
    if body is None:
        raise AnalysisError("host-tier connection died mid-frame")
    return body[:1], body[1:]


def _ser_tracker(tables: dict[int, dict[int, int]]) -> list:
    return [
        [int(acl), [[int(s), int(e)] for s, e in t.items()]]
        for acl, t in tables.items()
    ]


def _ser_quarantine(q: dict[tuple, int]) -> list:
    return [
        [fw, acl, int(idx), text, int(h)]
        for (fw, acl, idx, text), h in sorted(q.items())
    ]


def _de_quarantine(rows: list) -> dict[tuple, int]:
    return {
        (fw, acl, int(idx), text): int(h) for fw, acl, idx, text, h in rows
    }


# ---------------------------------------------------------------------------
# The per-host ingest worker.
# ---------------------------------------------------------------------------

#: Thread-mode hosts share ONE process and therefore ONE xla:cpu client;
#: concurrent shard_map executes from different host threads can cross
#: their collective rendezvous and wedge until the collective timeout
#: (the oversubscribed-host load artifact the tests/conftest.py
#: calibration note describes).  Thread-mode hosts therefore take this
#: gate around device execution — blocking until the step's outputs are
#: ready before releasing — so at most one collective program is in
#: flight per client.  Process workers (the production mode) never take
#: it: each owns its own client and keeps the full async pipeline.
_THREAD_STEP_GATE = threading.Lock()


class HostServeDriver(ServeDriver):
    """One host's ingest tier of ``serve --distributed``.

    A full :class:`ServeDriver` with publication handed to rank 0: the
    ``_emit_epoch`` hook ships every closed window to the merge plane
    and ``_publish`` keeps only the in-memory report (debug surface; no
    disk, no diffs, no cumulative render — rank 0 owns all of that).
    The worker NEVER checkpoints its ring (``checkpoint_every_windows``
    is forced to 0 by the supervisor): durability is the per-host WAL +
    rank 0's merged-ring checkpoint, and a rejoining worker replays its
    WAL tail past ``wal_resume_seq`` (the last seq rank 0 merged).
    """

    #: this tier's lineage records (host-local ledger under host-<r>/);
    #: the supervisor assembles the authoritative "dist" records from
    #: the shipped per-epoch extras
    _lineage_kind = "host"

    def __init__(
        self,
        rank: int,
        emit,
        ruleset_prefix: str,
        cfg: AnalysisConfig,
        scfg: ServeConfig,
        *,
        topk: int = 10,
        start_window: int = 0,
        wal_resume_seq: int = 0,
        serialize_dispatch: bool = False,
        spool_dir: str = "",
        spool_budget_mb: int = 0,
        spool_resume: bool = False,
    ):
        super().__init__(ruleset_prefix, cfg, scfg, topk=topk)
        self.rank = rank
        self._emit = emit  # callable(kind: bytes, body: bytes)
        self._dispatch_gate = (
            _THREAD_STEP_GATE if serialize_dispatch else None
        )
        self._start_window = start_window
        self._given_wal_seq = wal_resume_seq
        self._seeded = False
        self._gauge_next = 0.0
        self._retire_req = False
        self._retiring = False
        self._kill_req = False  # chaos seam: abrupt in-process host death
        # external stop (supervisor S-frame / signal), as opposed to the
        # local max_windows finish: only the former aborts the final
        # backlog drain (_teardown sets _stop_req on EVERY exit path, so
        # it cannot distinguish the two)
        self._ext_stop = threading.Event()
        # durable epoch spool (DESIGN §23): every closed window's packed
        # epoch is appended here BEFORE it ships, so it survives this
        # host AND any supervisor; a failover successor replays it
        self._ship_backlog: list[bytes] = []  # parked by partition mode
        self._spool: EpochSpool | None = None
        if spool_dir and spool_budget_mb > 0:
            try:
                self._spool = EpochSpool(
                    spool_dir, budget_bytes=spool_budget_mb << 20
                )
                if not spool_resume:
                    # a fresh (non-rejoin) start must not leave a stale
                    # spool for a later failover to replay into new ids
                    self._spool.reset()
            except (WalQuarantine, OSError) as e:
                self._spool = None
                self._degrade("spool", e)

    # -- control surface (reader thread / supervisor) ---------------------
    def request_retire(self) -> None:
        """Planned retirement: stop ingress, drain the queue into one
        final window marked ``retired`` — never a silent drop."""
        self._retire_req = True

    def kill(self) -> None:
        """Abrupt death for the in-process chaos tests: the serve loop
        raises at its next tick, losing the open window exactly like a
        SIGKILL would (minus what the WAL already spooled)."""
        self._kill_req = True

    def stop(self) -> None:
        self._ext_stop.set()
        super().stop()

    # -- overridden device dispatch ---------------------------------------
    def _run_chunk(self, batch_np: np.ndarray) -> None:
        gate = self._dispatch_gate
        if gate is None:
            return super()._run_chunk(batch_np)
        import jax

        with gate:
            super()._run_chunk(batch_np)
            jax.block_until_ready(self.state)

    def _run_chunk6(self, batch6_np: np.ndarray) -> None:
        gate = self._dispatch_gate
        if gate is None:
            return super()._run_chunk6(batch6_np)
        import jax

        with gate:
            super()._run_chunk6(batch6_np)
            jax.block_until_ready(self.state)

    # -- overridden window lifecycle --------------------------------------
    def _begin_window(self) -> None:
        if not self._seeded:
            self._seeded = True
            # joining at the merge frontier (scale-out, rejoin): the
            # first local window takes the supervisor-assigned id so
            # merged window ids stay globally consistent
            if self.win_id < self._start_window:
                self.win_id = self._start_window
        super()._begin_window()

    def _restore_ring(self) -> None:
        # a host worker has no on-disk ring (rank 0 owns the merged-ring
        # checkpoint); "resume" here means REJOIN — replay the local WAL
        # tail past the last seq the merge plane already published
        self._wal_resume_seq = self._given_wal_seq

    def _window_meta(self, *, partial: bool) -> dict:
        meta = super()._window_meta(partial=partial)
        meta["host"] = self.rank
        if self._retiring:
            # the retirement drain closed the listeners on purpose: that
            # is not lost traffic, so the listener-death reasons come
            # off; genuine drops (queue overflow before the drain) stay
            meta["retired"] = True
            inc = meta.get("incomplete")
            if inc:
                inc["reasons"] = [
                    r for r in inc["reasons"]
                    if r not in ("listener_died", "listener_down")
                ]
                if not inc["reasons"]:
                    del meta["incomplete"]
        return meta

    def _emit_epoch(self, ep: WindowEpoch) -> None:
        extra = {
            "rank": self.rank,
            "meta": ep.meta,
            "tracker": _ser_tracker(ep.tracker_tables),
            "quarantine": _ser_quarantine(ep.quarantine),
            # label map only (digest -> full src128 for report
            # rendering): union-merged at rank 0 via setdefault, which
            # cannot affect register counts
            "v6_digests": [
                [int(d), int(s)] for d, s in self._v6_digests.items()
            ],
            "wal_next": int(self._wal_next),
            # the closed window's inclusive WAL low bound (the next
            # window is already open here, so _win_wal_lo has advanced):
            # rank 0 stamps [wal_lo, wal_next) into the dist lineage
            "wal_lo": int(getattr(self, "_prev_win_wal_lo", 0)),
            "degraded": self.degraded_set(),
        }
        payload = pack_epoch_payload(ep.arrays, extra)
        if self._spool is not None:
            try:
                self._spool.append_epoch(payload)
            except (AnalysisError, OSError) as e:
                # full/readonly spool volume: the epoch still SHIPS (the
                # live merge is unaffected) — only failover durability
                # degrades, and /health says so
                self._degrade("spool", e)
                obs.instant("serve.host.spool_fail", args={
                    "host": self.rank, "window": ep.meta.get("id"),
                })
        if self._ship_backlog:
            # partition mode: epochs must reach the supervisor in window
            # order, so nothing ships until the backlog drains at heal
            self._ship_backlog.append(payload)
            return
        self._ship_or_park(payload)

    def _ship_attempt(self, payload: bytes, kind: bytes = b"E") -> None:
        # chaos site: the ship connection fails (severed merge-plane
        # link / partition analog); the retry seam absorbs a transient
        # burst, exhaustion parks the epoch in the partition backlog.
        # b"F" marks an epoch arriving via the backlog-heal drain so
        # rank 0 can stamp path="backlog_heal" on the window's lineage
        faults.fire("dist.epoch.ship")
        self._emit(kind, payload)

    def _ship_or_park(self, payload: bytes) -> None:
        try:
            retrypolicy.call("dist.epoch.ship", lambda: self._ship_attempt(payload))
        except (AnalysisError, OSError) as e:
            self._ship_backlog.append(payload)
            self._degrade(f"partition:{self.rank}", e)
            obs.instant("serve.host.partition", args={
                "host": self.rank, "backlog": len(self._ship_backlog),
            })

    def _heal_partition(self) -> None:
        """Drain the parked epochs in order (one probe per gauge tick);
        the spool already holds them, so a persistent partition costs
        latency, never data — zero silent drops on heal."""
        while self._ship_backlog:
            try:
                self._ship_attempt(self._ship_backlog[0], kind=b"F")
            except (AnalysisError, OSError):
                return  # still partitioned; next tick probes again
            self._ship_backlog.pop(0)
        self._recover(f"partition:{self.rank}")
        obs.instant("serve.host.partition_heal", args={"host": self.rank})

    def _publish(self, rep_obj: dict, prev: dict | None, meta: dict) -> None:
        # rank 0 owns publication; the worker keeps only the in-memory
        # window map (bounded by the ring) as a debug surface.  The
        # host-tier lineage record still ledgers locally (kind "host",
        # host-<r>/lineage.jsonl): the doctor joins it against rank 0's
        # "dist" records when diagnosing which tier lost a window
        lin = rep_obj.get("totals", {}).get("lineage")
        if lin is not None:
            self._lineage_append(lin)
        with self._pub_lock:
            self._published["report"] = rep_obj
            self._window_reports[meta["id"]] = rep_obj
            live = set(self.ring.window_ids())
            for wid in [w for w in self._window_reports if w not in live]:
                del self._window_reports[wid]

    def _maybe_autoscale(self) -> None:
        super()._maybe_autoscale()  # canonical-signal sampling (no engine)
        if self._kill_req:
            raise AnalysisError(
                f"serve host {self.rank} killed (injected host death)"
            )
        if self._retire_req and not self._retiring:
            self._retiring = True
            obs.instant("serve.host.retire", args={"host": self.rank})
            # stop ingress; the serve loop then drains the queue and
            # exits through its clean all-ingress-closed path, rotating
            # the remainder into one final marked window
            self.listeners.close()
        now = time.monotonic()
        if now >= self._gauge_next:
            self._gauge_next = now + 0.5
            self._emit_gauges()

    def _emit_gauges(self) -> None:
        if self._ship_backlog:
            self._heal_partition()
        gauges = self.metrics_gauges()
        gauges["spool_depth"] = len(self._ship_backlog)
        gauges["spool_seq"] = (
            int(self._spool.next_seq) if self._spool is not None else 0
        )
        try:
            self._emit(b"G", json.dumps({
                "rank": self.rank,
                "gauges": gauges,
                "degraded": self.degraded_set(),
                "addresses": self.listeners.addresses(),
            }).encode("utf-8"))
        except OSError:
            pass  # gauge frames are advisory; epochs have the
            # retry/backlog plane, and the supervisor's monitor
            # owns death detection

    def run(self) -> dict:
        try:
            summary = super().run()
            self._drain_backlog_final()
            summary["degraded"] = self.degraded_set()
            return summary
        finally:
            if self._spool is not None:
                self._spool.close()  # fsync: the tail survives a crash

    def _drain_backlog_final(self) -> None:
        """Clean-finish barrier: a parked epoch must not die with its
        producer when the partition is healable — keep probing until
        the backlog drains or a stop tears the host down.  A stop
        during a persistent partition is NOT a drop: the spool holds
        every parked epoch durably for the elected successor's replay.
        """
        while self._ship_backlog and not self._ext_stop.is_set():
            # the gauge frame keeps the drain observable (spool_depth,
            # partition marker) AND probes the heal path each tick
            self._emit_gauges()
            if self._ship_backlog:
                self._ext_stop.wait(0.5)  # still partitioned; re-probe


# ---------------------------------------------------------------------------
# Process-mode worker entry (multiprocessing spawn target).
# ---------------------------------------------------------------------------


def _worker_entry(spec_json: str) -> None:
    """Spawn target: rebuild configs, connect to rank 0, run the host.

    Flight-recorder inheritance mirrors the RA_TRACE_DIR discipline:
    the supervisor arms with ``export_env=True`` (publishing
    RA_BLACKBOX_DIR), and the worker arms FROM the environment with
    ``export_env=False`` — its shard lands in the same directory for
    the doctor's cross-host postmortem merge without stealing run
    ownership or pruning live sibling shards.
    """
    spec = json.loads(spec_json)
    rank = int(spec["rank"])
    bb = os.environ.get(flightrec.ENV_VAR, "")
    if bb:
        flightrec.arm(bb, role=f"serve-host{rank}", export_env=False)
    cfg = AnalysisConfig.from_dict(spec["cfg"])
    sdict = dict(spec["scfg"])
    sdict["listen"] = tuple(sdict.get("listen", ()))
    sdict["views"] = tuple(sdict.get("views", ()))
    scfg = ServeConfig(**sdict)
    host, _, port = spec["merge_addr"].rpartition(":")
    conn = socket.create_connection((host, int(port)), timeout=30.0)
    conn.settimeout(None)
    send_lock = threading.Lock()

    def emit(kind: bytes, body: bytes) -> None:
        with send_lock:
            _send_frame(conn, kind, body)

    drv = HostServeDriver(
        rank, emit, spec["prefix"], cfg, scfg,
        topk=int(spec["topk"]),
        start_window=int(spec["start_window"]),
        wal_resume_seq=int(spec["wal_resume_seq"]),
        spool_dir=spec.get("spool_dir", ""),
        spool_budget_mb=int(spec.get("spool_budget_mb", 0)),
        spool_resume=bool(spec.get("spool_resume", False)),
    )

    def control_reader() -> None:
        try:
            while True:
                fr = _recv_frame(conn)
                if fr is None:
                    break
                kind, _body = fr
                if kind == b"R":
                    drv.request_retire()
                elif kind == b"S":
                    drv.stop()
        except (OSError, AnalysisError):
            pass  # supervisor died: the worker stops on its own terms
        drv.stop()

    emit(b"H", json.dumps({"rank": rank, "pid": os.getpid()}).encode())
    threading.Thread(
        target=control_reader, name=f"ra-host{rank}-ctl", daemon=True
    ).start()
    code = 0
    try:
        summary = drv.run()
        emit(b"B", json.dumps({
            "rank": rank, "summary": summary,
            "wal_next": int(drv._wal_next),
        }).encode())
    except BaseException as e:
        try:
            emit(b"B", json.dumps({
                "rank": rank, "error": f"{type(e).__name__}: {e}"[:500],
                "wal_next": int(getattr(drv, "_wal_next", 0)),
            }).encode())
        except OSError:
            pass
        code = 1
    finally:
        try:
            conn.close()
        except OSError:
            pass
    raise SystemExit(code)


# ---------------------------------------------------------------------------
# Rank 0: merge + publication supervisor.
# ---------------------------------------------------------------------------


class _Host:
    """Supervisor-side state of one ingest host (any worker mode)."""

    def __init__(self, rank: int, start_window: int):
        self.rank = rank
        self.start_window = start_window
        self.generation = 0
        self.finished = False  # clean BYE received
        self.dead = False  # unexpected death (SIGKILL, typed abort)
        self.dead_reason = ""
        self.dead_from: int | None = None  # first window id lost to death
        self.dead_until: int | None = None  # respawn rejoin window
        self.retiring = False
        self.stop_sent = False  # stop control delivered to THIS generation
        self.last_wid = -1  # highest window id submitted
        self.final_wid: int | None = None  # last wid at clean finish
        self.wal_recv = 0  # wal_next of the last RECEIVED epoch
        self.wal_ckpt = 0  # wal_next covered by PUBLISHED windows
        self.gauges: dict = {}
        self.degraded: list[str] = []
        self.addresses: dict = {}
        self.proc = None  # multiprocessing handle (process mode)
        self.thread: threading.Thread | None = None  # thread mode
        self.driver: HostServeDriver | None = None  # thread mode
        self.conn: socket.socket | None = None  # process mode
        self.send_lock = threading.Lock()
        self.summary: dict | None = None

    @property
    def live(self) -> bool:
        return not (self.finished or self.dead)


class _DropsQueue:
    """Queue shim so the borrowed cumulative renderer reads the merged
    drop total where the single-host driver reads its listener queue."""

    def __init__(self, drv: "DistServeDriver"):
        self._drv = drv

    def snapshot(self) -> dict:
        return {"dropped": int(self._drv.live_drops)}


class DistServeDriver:
    """Rank 0 of ``serve --distributed``: spawn the per-host ingest
    workers, merge their window epochs in id order under the
    ``_merge_tail`` laws, and own every publication surface.

    Renders through the SAME code paths as the single-host driver —
    ``_publish``, ``_render_merged``, ``_render_cumulative``,
    ``merged_report_obj`` and the HTTP server are borrowed from
    :class:`ServeDriver` unbound — so the published report of a merged
    window is bit-identical to a single-host replay of the union of
    the hosts' delivered lines by construction, not by re-implementation.
    """

    def __init__(
        self,
        ruleset_prefix: str,
        cfg: AnalysisConfig,
        scfg: ServeConfig,
        dscfg: DistServeConfig,
        *,
        topk: int = 10,
        ascfg: AutoscaleConfig | None = None,
    ):
        if cfg.mesh_shape != "hybrid":
            raise AnalysisError(
                "serve --distributed realizes the hybrid DCN x ICI "
                "topology (the host tier IS the dcn axis); pass --mesh "
                "hybrid"
            )
        if scfg.static_analysis:
            raise AnalysisError(
                "serve --distributed does not run the static analyzer "
                "yet (rank 0 holds no device mesh); run `analyze` "
                "offline or serve single-host with --static-analysis"
            )
        if not scfg.listen:
            raise AnalysisError(
                "serve needs at least one --listen spec "
                "(udp:HOST:PORT, tcp:HOST:PORT, or tail:PATH)"
            )
        self.prefix = ruleset_prefix
        self.cfg = cfg
        self.scfg = scfg
        self.dscfg = dscfg
        self.topk = topk
        self.ascfg = ascfg
        try:
            self.packed = pack_mod.load_packed(ruleset_prefix)
        except OSError as e:
            raise AnalysisError(
                f"cannot read packed ruleset {ruleset_prefix!r}: {e}"
            ) from e
        # the worker cfg is derived ONCE: each host runs a flat local
        # mesh (the hybrid topology's inner ICI axis); the outer dcn
        # axis is realized by the host-tier merge below
        self._worker_cfg = cfg.replace(
            mesh_shape="flat", mesh_dcn=0, resume=False, blackbox_dir=""
        )
        self._fp = (
            ckpt.fingerprint(self.packed, cfg, dscfg.ladder_max, 0)
            + "-distserve"
        )
        # supervisor lease + fencing term (DESIGN §23): 0 until a lease
        # is won; every published artifact, gauge, and checkpoint
        # fingerprint carries it, and losing the lease turns every
        # publication path into a typed SupervisorFenced abort
        self.term = 0
        self._lease: SupervisorLease | None = None
        self._fenced_seen: tuple[int, str] | None = None
        self._sup_kill = False  # chaos seam: abrupt supervisor death
        self.spool_replayed_total = 0  # epochs replayed at takeover
        self.replay_windows_total = 0  # windows published from replay
        self.replay_lag_windows = 0  # frontier lag measured at takeover
        self.replay_refused_total = 0  # corrupt spooled epochs refused
        # merged publication state (mirrors ServeDriver so its unbound
        # render/publish methods run here unchanged)
        self.ring = WindowRing(scfg.ring)
        self.cum_arrays = zero_arrays(self.packed.n_keys, cfg)
        self.cum_tracker = TopKTracker(cfg.sketch.topk_capacity)
        self.cum_quarantine: dict[tuple, int] = {}
        self.cum_incomplete_reasons: list[str] = []
        self.cum_incomplete_windows: list[int] = []
        self._v6_digests: dict[int, int] = {}
        self._static_obj = None  # distributed serve: no static plane
        self.windows_published = 0
        self.total_lines = 0
        self.total_parsed = 0
        self.total_skipped = 0
        self.total_chunks = 0
        self.live_drops = 0  # merged drops published this process
        self.drops_restored = 0  # from the restored checkpoint
        self.reloads = 0  # no hot reload in distributed v1 (DESIGN §22)
        self.lat_cum = LatencyHistogram()  # per-host SLO histograms stay
        self.queue = _DropsQueue(self)     # per-host; shims for borrows
        self._pub_lock = threading.Lock()
        self._published: dict[str, dict] = {}
        self._window_reports: dict[int, dict] = {}
        self._deg_lock = threading.Lock()
        self.degraded: dict[str, str] = {}
        self.degraded_events = 0
        self.recovered_events = 0
        # merge plane
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.hosts: dict[int, _Host] = {}
        self.next_wid = 0
        self._pending: dict[int, dict[int, tuple[dict, dict]]] = {}
        self._arrival: dict[int, float] = {}
        self._host_wal_restored: dict[int, int] = {}
        self.late_epochs = 0
        self.late_epoch_lines = 0
        self.skipped_windows: list[int] = []
        self.hosts_spawned = 0
        self.hosts_dead_total = 0
        self.hosts_retired_total = 0
        self._stop_req = threading.Event()
        self._old_signals: dict = {}
        self._engine: PolicyEngine | None = None
        self._ladder: list[int] = []
        self._as_next = 0.0
        self._msock: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._accept_stop = False
        self._t0 = time.time()
        # lineage + SLO + trend state (DESIGN §24): the same shared
        # initializer ServeDriver's ctor calls, so the borrowed
        # _publish finds identical attributes here.  term resets to 0
        # (matching the line above); run() overwrites it at lease win
        self._init_lineage_plane()
        # bind the endpoints HERE, like ServeDriver: a bad --http or
        # --dist-merge-bind port must be the documented clean bind
        # error (exit 2), never a mid-run failure with traffic flowing
        self._http = None
        self._http_thread = None
        if scfg.http != "off":
            host, _, port = scfg.http.rpartition(":")
            self._http = _make_http_server((host, int(port)), self)
        if dscfg.workers == "process":
            host, _, port = dscfg.merge_bind.rpartition(":")
            try:
                self._msock = socket.create_server(
                    (host, int(port)), backlog=16
                )
                self._msock.settimeout(0.5)
            except BaseException:
                if self._http is not None:
                    self._http.server_close()
                raise

    # borrowed single-host surfaces: identical rendering/publication by
    # construction (the bit-identity tentpole), one implementation to
    # audit.  Each reads only attributes this class also maintains.
    published = ServeDriver.published
    window_report = ServeDriver.window_report
    merged_report_obj = ServeDriver.merged_report_obj
    _render_merged = ServeDriver._render_merged
    _render_window_obj = ServeDriver._render_window_obj
    _window_totals = ServeDriver._window_totals
    _attach_static = ServeDriver._attach_static
    _render_cumulative = ServeDriver._render_cumulative
    _publish = ServeDriver._publish
    _write_json = ServeDriver._write_json
    _degrade = ServeDriver._degrade
    _recover = ServeDriver._recover
    degraded_set = ServeDriver.degraded_set
    render_latency_prom = ServeDriver.render_latency_prom
    _init_lineage_plane = ServeDriver._init_lineage_plane
    _lineage_append = ServeDriver._lineage_append
    lineage_record = ServeDriver.lineage_record
    _observe_slo = ServeDriver._observe_slo
    _rule_labels = ServeDriver._rule_labels
    _spill_epoch = ServeDriver._spill_epoch
    range_report_obj = ServeDriver.range_report_obj

    def lineage_tail(self) -> dict:
        """The ``/lineage`` view plus the live leadership snapshot: who
        holds the publication right the records' term stamps refer to."""
        out = ServeDriver.lineage_tail(self)
        if self._lease is not None:
            out["lease"] = self._lease.describe()
        return out

    # -- public control ---------------------------------------------------
    def stop(self) -> None:
        self._stop_req.set()
        with self._cond:
            self._cond.notify_all()

    @property
    def http_address(self) -> tuple[str, int] | None:
        srv = self._http
        return tuple(srv.server_address[:2]) if srv is not None else None

    @property
    def merge_address(self) -> tuple[str, int] | None:
        s = self._msock
        return tuple(s.getsockname()[:2]) if s is not None else None

    def live_hosts(self) -> list[int]:
        with self._lock:
            return sorted(r for r, h in self.hosts.items() if h.live)

    def kill_host(self, rank: int) -> None:
        """Chaos surface: abrupt whole-host death (tests + drills).

        Thread mode injects a crash into the worker loop; process mode
        SIGKILLs the worker process — either way the supervisor's death
        path (typed incomplete windows naming the host, degraded
        service, optional respawn) is what's being exercised.
        """
        with self._lock:
            h = self.hosts.get(rank)
        if h is None:
            raise AnalysisError(f"no such serve host: {rank}")
        if h.driver is not None:
            h.driver.kill()
        elif h.proc is not None:
            h.proc.kill()

    def kill_supervisor(self) -> None:
        """Chaos surface: abrupt merge/publication-supervisor death.

        The merge loop raises at its next tick, dying with whatever
        epochs were pending unpublished — exactly what a SIGKILL costs
        (the per-host spools keep them; an elected successor replays).
        """
        self._sup_kill = True
        with self._cond:
            self._cond.notify_all()

    # -- lease / failover --------------------------------------------------
    def _spool_root(self) -> str:
        return self.dscfg.spool_dir or self.scfg.serve_dir

    def _lease_dir(self) -> str:
        return os.path.join(self._spool_root(), "lease")

    def _host_spool_dir(self, rank: int) -> str:
        root = self.dscfg.spool_dir
        if root:
            return os.path.join(root, f"host-{rank}")
        return os.path.join(self.scfg.serve_dir, f"host-{rank}", "spool")

    def _on_lease_fenced(self) -> None:
        """Heartbeat-thread callback: a HIGHER term was observed."""
        if self._lease is not None:
            self._fenced_seen = self._lease.observed()
            obs.instant("lease.fenced", args={
                "term": self.term,
                "winner_term": self._fenced_seen[0],
                "winner": self._fenced_seen[1],
            })
            flightrec.cursor(fenced_by_term=self._fenced_seen[0])
        self.stop()

    def _check_fenced(self) -> None:
        """Raise typed BEFORE any externally visible effect once this
        supervisor may no longer publish (observed a higher term, or its
        own renewals aged past the TTL).  Called on every publication,
        checkpoint, and merge-loop pass — the split-brain half of the
        DESIGN §23 argument (the other half is the lease's 1.5x steal
        margin)."""
        L = self._lease
        if L is None or not L.fenced:
            return
        t, h = self._fenced_seen or L.observed()
        raise SupervisorFenced(
            f"stale supervisor fenced: this process held term {self.term} "
            f"but term {t} is now held by {h!r} (or renewals aged past the "
            f"{L.ttl:.1f}s TTL); publishing would risk two publications "
            "for one window id — the successor replays the epoch spools "
            "and publishes bit-identically instead"
        )

    def failover_gauges(self) -> dict:
        """Leader/lease/replay gauges — merged into ``metrics_gauges``
        so the JSON /metrics block and the prom families carry the SAME
        values (audit_distserve parity)."""
        L = self._lease
        return {
            "leader_term": self.term,
            "lease_age_sec": round(L.age(), 3) if L is not None else 0.0,
            "lease_fenced": int(L.fenced) if L is not None else 0,
            "spool_replayed_total": self.spool_replayed_total,
            "replay_windows_total": self.replay_windows_total,
            "replay_lag_windows": self.replay_lag_windows,
        }

    # -- health / metrics -------------------------------------------------
    def health(self) -> dict:
        with self._lock:
            hosts = {
                str(r): {
                    "live": h.live,
                    "finished": h.finished,
                    "dead": h.dead,
                    **({"dead_reason": h.dead_reason} if h.dead else {}),
                    "retiring": h.retiring,
                    "generation": h.generation,
                    "start_window": h.start_window,
                    "last_window": h.last_wid,
                    "degraded": list(h.degraded),
                    "addresses": h.addresses,
                }
                for r, h in sorted(self.hosts.items())
            }
            dead = sorted(r for r, h in self.hosts.items() if h.dead)
            live = sum(1 for h in self.hosts.values() if h.live)
            pending = len(self._pending)
        with self._pub_lock:
            ring_windows = self.ring.window_ids()
            quarantine_hits = int(sum(self.cum_quarantine.values()))
        deg = self.degraded_set()
        host_deg = sorted({
            f"host{r}:{s}"
            for r, h in self.hosts.items() for s in h.degraded
        })
        degraded = bool(dead or deg or host_deg or self.live_drops)
        return {
            "status": "degraded" if degraded else "ok",
            "distributed": True,
            "term": self.term,
            "degraded_subsystems": deg + host_deg,
            "degraded_events": self.degraded_events,
            "recovered_events": self.recovered_events,
            "uptime_sec": round(time.time() - self._t0, 3),
            "windows_published": self.windows_published,
            "next_window": self.next_wid,
            "merge_pending_windows": pending,
            "lines_total": self.total_lines,
            "drops_total": self.live_drops + self.drops_restored,
            "late_epochs": self.late_epochs,
            "skipped_windows": list(self.skipped_windows),
            "hosts": hosts,
            "hosts_live": live,
            "dead_hosts": dead,
            "world": live,
            "ruleset": {
                "n_rules": self.packed.n_rules,
                "n_acls": self.packed.n_acls,
                "n_keys": self.packed.n_keys,
            },
            "window": {
                "mode": "lines" if self.scfg.window_lines else "sec",
                "length": self.scfg.window_lines or self.scfg.window_sec,
                "ring": self.scfg.ring,
                "ring_windows": ring_windows,
            },
            "quarantine_hits": quarantine_hits,
            **(
                {"autoscale": self._engine.summary()}
                if self._engine is not None
                else {}
            ),
        }

    def host_gauges(self) -> dict[str, dict]:
        """Per-host flat gauge blocks, host rank as the label value.

        ONE source of truth for the JSON ``/metrics`` ``hosts`` block
        AND the labeled Prometheus families on ``format=prom`` — the
        parity verify/registry.py::audit_distserve pins.
        """
        with self._lock:
            out = {}
            for r, h in sorted(self.hosts.items()):
                out[str(r)] = {
                    **h.gauges,
                    "live": int(h.live),
                    "dead": int(h.dead),
                    "degraded_subsystems": len(h.degraded),
                    "generation": h.generation,
                    "last_window": h.last_wid,
                }
            return out

    def metrics_gauges(self) -> dict:
        with self._lock:
            live = sum(1 for h in self.hosts.values() if h.live)
            pending = len(self._pending)
            rate = sum(
                h.gauges.get("lines_per_sec", 0.0)
                for h in self.hosts.values() if h.live
            )
            qdepth = max(
                (h.gauges.get("queue_depth", 0) for h in self.hosts.values()),
                default=0,
            )
        g = {
            "hosts_live": live,
            "hosts_spawned_total": self.hosts_spawned,
            "hosts_dead_total": self.hosts_dead_total,
            "hosts_retired_total": self.hosts_retired_total,
            "windows_published": self.windows_published,
            "next_window": self.next_wid,
            "merge_pending_windows": pending,
            "lines_windowed_total": self.total_lines,
            "drops_total": self.live_drops + self.drops_restored,
            "late_epochs_total": self.late_epochs,
            "late_epoch_lines_total": self.late_epoch_lines,
            "skipped_windows_total": len(self.skipped_windows),
            "lines_per_sec": round(rate, 1),
            "queue_depth_max": qdepth,
            "world": live,
            "degraded_subsystems": len(self.degraded_set()),
            "degraded_events_total": self.degraded_events,
            "recovered_events_total": self.recovered_events,
        }
        if self.scfg.lineage:
            g["lineage_records_total"] = self.lineage_records_total
            g["trend_events_total"] = self.trend_events_total
        if self.epoch_store is not None:
            g.update(self.epoch_store.gauges())
            g.update(self.lat_range.gauges("latency_range_query_"))
        if self._suffix is not None:
            g.update({
                "merged_suffix_hits_total": self._suffix.hits,
                "merged_suffix_misses_total": self._suffix.misses,
            })
        if self.slo is not None:
            g.update(self.slo.gauges())
        g.update(self.failover_gauges())
        g.update(retrypolicy.gauges())
        eng = self._engine
        if eng is not None:
            g.update({
                "autoscale_decisions_total": len(eng.decisions),
                "autoscale_scale_out_total": sum(
                    1 for d in eng.decisions if d.direction == "out"
                ),
                "autoscale_scale_in_total": sum(
                    1 for d in eng.decisions if d.direction == "in"
                ),
                "autoscale_flaps_total": eng.flaps,
                "autoscale_budget_left": eng.budget_left,
            })
        return g

    def _sample_metrics(self) -> dict:
        return {"hosts": self.host_gauges()}

    def build_info_dict(self) -> dict:
        """Static build identity for ``ra_build_info`` (no ``world``
        attribute here: the mesh label carries the host-tier width)."""
        return build_info({
            "mesh": f"{self.cfg.mesh_shape}/{self.dscfg.hosts}",
        })

    def render_labeled_prom(self) -> str:
        """Host-labeled Prometheus families from the SAME per-host gauge
        blocks the JSON ``/metrics`` serves (audit_distserve parity),
        plus the build-info and objective-labeled SLO families every
        serve tier exports."""
        out = render_build_info_prom(self.build_info_dict())
        if self.slo is not None:
            out += render_prom_labeled(
                self.slo.labeled_gauges(),
                prefix="ra_serve_", label="objective",
            )
        return out + render_prom_labeled(
            self.host_gauges(), prefix="ra_serve_host_", label="host"
        )

    # -- run --------------------------------------------------------------
    def run(self) -> dict:
        scfg = self.scfg
        os.makedirs(scfg.serve_dir, exist_ok=True)
        armed_here = faults.arm_spec(self.cfg.fault_plan)
        retrypolicy.configure(self.cfg.retry_policy)
        if self.cfg.blackbox_dir:
            # run OWNER: export RA_BLACKBOX_DIR so spawned host workers
            # shard into the same directory (the doctor merges them)
            flightrec.arm(self.cfg.blackbox_dir, role="serve-sup")
        aborted: BaseException | None = None
        try:
            if self.ascfg is not None:
                self._ladder = host_ladder(
                    self.dscfg.min_hosts, self.dscfg.ladder_max
                )
                if self.dscfg.hosts not in self._ladder:
                    raise AnalysisError(
                        f"--dist-hosts {self.dscfg.hosts} is not on the "
                        f"host ladder {self._ladder}"
                    )
                self._engine = PolicyEngine(
                    self.ascfg, world=self.dscfg.hosts, ladder=self._ladder
                )
            if self.dscfg.lease_ttl_sec > 0:
                ttl = self.dscfg.lease_ttl_sec
                self._lease = SupervisorLease(
                    self._lease_dir(),
                    holder=f"{socket.gethostname()}:pid{os.getpid()}",
                    ttl_sec=ttl,
                )
                t_wait = time.monotonic()
                # blocks until this process wins a term: behind a live
                # incumbent it waits out the 1.5x-TTL staleness window,
                # so the previous holder has provably self-fenced first
                self.term = self._lease.acquire(
                    stop=self._stop_req, timeout=max(30.0, 10 * ttl)
                )
                obs.instant("lease.acquired", args={
                    "term": self.term,
                    "holder": self._lease.holder,
                    "wait_sec": round(time.monotonic() - t_wait, 3),
                })
                flightrec.cursor(term=self.term)
                self._lease.start_heartbeat(on_fenced=self._on_lease_fenced)
            if self.cfg.resume:
                self._restore()
            if scfg.epoch_store:
                # rank 0 spills MERGED windows only (DESIGN §25) —
                # host tiers keep no history; opened before the spool
                # replay so replayed windows land like live ones (the
                # store dedupes ids below its frontier)
                self.epoch_store = epochstore.EpochStore(
                    scfg.epoch_store,
                    budget_bytes=scfg.epoch_store_budget_bytes,
                    trend_threshold=scfg.trend_threshold,
                )
                if not self.cfg.resume:
                    self.epoch_store.reset()
                self.epoch_store.bind_base(self.next_wid)
                self.epoch_store.set_labels(
                    self._rule_labels(self.packed)
                )
            if scfg.lineage:
                # rank 0's provenance ledger (DESIGN §24), opened BEFORE
                # the takeover replay so the successor's replayed
                # windows ledger here like any live publication
                lpath = os.path.join(scfg.serve_dir, LineageLog.NAME)
                if self.cfg.resume:
                    live = set(self.ring.window_ids())
                    for r in LineageLog.read(lpath):
                        if (
                            r.get("kind") != "merged"
                            and r.get("window") in live
                        ):
                            self._lineage_recent[r["window"]] = r
                            self.lineage_records_total += 1
                else:
                    try:
                        os.remove(lpath)
                    except OSError:
                        pass
                self._lineage_log = LineageLog(lpath)
            if self.cfg.resume:
                self._replay_spools()
            obs.register_sampler("distserve", self.metrics_gauges)
            if self._msock is not None:
                self._accept_thread = threading.Thread(
                    target=self._accept_loop, name="ra-distserve-accept",
                    daemon=True,
                )
                self._accept_thread.start()
            self._start_http()
            self._install_signals()
            for r in range(self.dscfg.hosts):
                self._spawn_host(r, rejoin=self.cfg.resume)
            self._write_json("endpoint.json", {
                "pid": os.getpid(),
                "distributed": True,
                "term": self.term,
                "hosts": self.dscfg.hosts,
                "http": list(self.http_address) if self.http_address else None,
                "merge": (
                    list(self.merge_address) if self.merge_address else None
                ),
                "serve_dir": os.path.abspath(scfg.serve_dir),
                "host_dirs": {
                    str(r): os.path.abspath(
                        os.path.join(scfg.serve_dir, f"host-{r}")
                    )
                    for r in range(self.dscfg.hosts)
                },
            })
            self._merge_loop()
        except BaseException as e:
            aborted = e
            raise
        finally:
            try:
                self._teardown(aborted)
            finally:
                if armed_here:
                    faults.disarm()
        with self._lock:
            host_summaries = {
                str(r): {
                    "generation": h.generation,
                    "dead": h.dead,
                    **({"dead_reason": h.dead_reason} if h.dead else {}),
                    "retired": h.retiring,
                    "last_window": h.last_wid,
                    **({"summary": h.summary} if h.summary else {}),
                }
                for r, h in sorted(self.hosts.items())
            }
            dead = sorted(r for r, h in self.hosts.items() if h.dead)
        summary = {
            "distributed": True,
            "term": self.term,
            "failover": {
                "spool_replayed": self.spool_replayed_total,
                "replay_windows": self.replay_windows_total,
                "replay_refused": self.replay_refused_total,
                "lease_renews": (
                    self._lease.renews if self._lease is not None else 0
                ),
            },
            "hosts": host_summaries,
            "hosts_spawned": self.hosts_spawned,
            "dead_hosts": dead,
            "hosts_retired": self.hosts_retired_total,
            "windows_published": self.windows_published,
            "lines_total": self.total_lines,
            "drops": self.live_drops + self.drops_restored,
            "late_epochs": self.late_epochs,
            "skipped_windows": list(self.skipped_windows),
            "quarantine_hits": int(sum(self.cum_quarantine.values())),
            "serve_dir": os.path.abspath(scfg.serve_dir),
            "world": self.dscfg.hosts,
            "degraded": self.degraded_set(),
            "retry": retrypolicy.counters(),
            **(
                {"epoch_store": self.epoch_store.stats()}
                if self.epoch_store is not None
                else {}
            ),
            **(
                {"autoscale": self._engine.summary()}
                if self._engine is not None
                else {}
            ),
        }
        self._write_json("summary.json", summary)
        return summary

    # -- worker lifecycle -------------------------------------------------
    def _spawn_host(self, rank: int, *, rejoin: bool) -> None:
        scfg = self.scfg
        host_dir = os.path.join(scfg.serve_dir, f"host-{rank}")
        wscfg = dataclasses.replace(
            scfg,
            listen=tuple(
                offset_listen_spec(s, rank) for s in scfg.listen
            ),
            http="off",
            serve_dir=host_dir,
            checkpoint_every_windows=0,
            checkpoint_dir="",
            reload_watch=False,
            views=(),
            wal_dir=os.path.join(host_dir, "wal") if scfg.wal else "",
            # burn-rate alerting runs at rank 0 over the MERGED windows;
            # per-host engines would double-fire every breach event
            slo="",
        )
        with self._lock:
            h = self.hosts.get(rank)
            if h is None:
                h = self.hosts[rank] = _Host(rank, self.next_wid)
            else:
                # respawn/rejoin: same rank, fresh generation, joining
                # at the merge frontier past its predecessor's last
                # submitted window
                h.generation += 1
                h.start_window = max(self.next_wid, h.last_wid + 1)
                h.finished = False
                h.dead = False
                h.dead_until = h.start_window
                h.retiring = False
                h.stop_sent = False
                # the replacement binds its own (ephemeral) ports; the
                # predecessor's addresses must not be served meanwhile
                h.addresses = {}
                h.gauges = {}
                h.conn = None
                h.driver = None
                h.proc = None
            start_window = h.start_window
            wal_seq = (
                max(h.wal_recv, self._host_wal_restored.get(rank, 0))
                if rejoin else 0
            )
            self.hosts_spawned += 1
        wcfg = self._worker_cfg.replace(resume=bool(rejoin and scfg.wal))
        spool_dir = (
            self._host_spool_dir(rank)
            if self.dscfg.spool_budget_mb > 0 else ""
        )
        obs.instant("serve.host.spawn", args={
            "host": rank, "rejoin": bool(rejoin),
            "start_window": start_window, "wal_seq": wal_seq,
        })
        if self.dscfg.workers == "thread":
            drv = HostServeDriver(
                rank,
                lambda kind, body, _r=rank: self._on_frame(_r, kind, body),
                self.prefix, wcfg, wscfg,
                topk=self.topk, start_window=start_window,
                wal_resume_seq=wal_seq, serialize_dispatch=True,
                spool_dir=spool_dir,
                spool_budget_mb=self.dscfg.spool_budget_mb,
                spool_resume=rejoin,
            )

            def runner(_r=rank, _drv=drv):
                try:
                    s = _drv.run()
                    self._on_frame(_r, b"B", json.dumps({
                        "rank": _r, "summary": s,
                        "wal_next": int(_drv._wal_next),
                    }).encode())
                except BaseException as e:
                    self._on_frame(_r, b"B", json.dumps({
                        "rank": _r,
                        "error": f"{type(e).__name__}: {e}"[:500],
                        "wal_next": int(getattr(_drv, "_wal_next", 0)),
                    }).encode())

            th = threading.Thread(
                target=runner, name=f"ra-serve-host{rank}", daemon=True
            )
            with self._lock:
                h.driver = drv
                h.thread = th
            th.start()
            return
        import multiprocessing as mp

        addr = self.merge_address
        spec = json.dumps({
            "rank": rank,
            "prefix": self.prefix,
            "cfg": wcfg.to_dict(),
            "scfg": dataclasses.asdict(wscfg),
            "topk": self.topk,
            "merge_addr": f"{addr[0]}:{addr[1]}",
            "start_window": start_window,
            "wal_resume_seq": wal_seq,
            "spool_dir": spool_dir,
            "spool_budget_mb": self.dscfg.spool_budget_mb,
            "spool_resume": bool(rejoin),
        })
        p = mp.get_context("spawn").Process(
            target=_worker_entry, args=(spec,),
            name=f"ra-serve-host{rank}", daemon=True,
        )
        p.start()
        with self._lock:
            h.proc = p

    def _send_control(self, h: _Host, kind: bytes) -> None:
        if h.driver is not None:
            if kind == b"R":
                h.driver.request_retire()
            elif kind == b"S":
                h.driver.stop()
            return
        if h.conn is not None:
            try:
                with h.send_lock:
                    _send_frame(h.conn, kind, b"")
            except OSError:
                pass  # death handled by the monitor/reader paths

    # -- frame dispatch (worker threads / conn readers) --------------------
    def _on_frame(self, rank: int, kind: bytes, body: bytes) -> None:
        if kind in (b"E", b"F"):
            arrays, extra = unpack_epoch_payload(body)
            # provenance stamps (DESIGN §24): the CRC is over the exact
            # shipped payload bytes — the spool holds those same bytes,
            # so a failover successor's replayed record carries the
            # identical crc (the replay-identity law, pinned in tests).
            # b"F" marks arrival via the partition backlog-heal drain
            extra["payload_crc"] = zlib.crc32(body) & 0xFFFFFFFF
            extra["healed"] = kind == b"F"
            wid = int(extra["meta"]["id"])
            with self._cond:
                h = self.hosts[rank]
                h.last_wid = max(h.last_wid, wid)
                h.wal_recv = max(h.wal_recv, int(extra.get("wal_next", 0)))
                if wid < self.next_wid:
                    # the window already published without this host
                    # (death/timeout marking named it): merging now
                    # would double-publish — drop with explicit
                    # accounting, never silently
                    self.late_epochs += 1
                    self.late_epoch_lines += int(extra["meta"].get("lines", 0))
                    obs.instant("serve.host.late_epoch", args={
                        "host": rank, "window": wid,
                        "lines": int(extra["meta"].get("lines", 0)),
                    })
                else:
                    self._pending.setdefault(wid, {})[rank] = (arrays, extra)
                    self._arrival.setdefault(wid, time.monotonic())
                self._cond.notify_all()
        elif kind == b"G":
            j = json.loads(body)
            with self._lock:
                h = self.hosts[rank]
                h.gauges = j.get("gauges", {})
                h.degraded = list(j.get("degraded", []))
                h.addresses = j.get("addresses", h.addresses)
        elif kind == b"B":
            j = json.loads(body)
            with self._cond:
                h = self.hosts[rank]
                h.wal_recv = max(h.wal_recv, int(j.get("wal_next", 0)))
                if "error" in j:
                    self._mark_dead_locked(h, j["error"])
                else:
                    h.finished = True
                    h.final_wid = h.last_wid
                    h.summary = j.get("summary")
                    if h.retiring:
                        self.hosts_retired_total += 1
                self._cond.notify_all()
        elif kind == b"H":
            pass  # liveness signal; conn binding happens in _conn_reader

    # -- death plane ------------------------------------------------------
    def _mark_dead_locked(self, h: _Host, reason: str) -> None:
        if h.dead or h.finished:
            return
        h.dead = True
        h.dead_reason = reason[:300]
        h.dead_from = max(self.next_wid, h.last_wid + 1)
        h.dead_until = None
        self.hosts_dead_total += 1
        obs.instant("serve.host.died", args={
            "host": h.rank, "reason": h.dead_reason,
        })
        flightrec.cursor(dead_hosts=sorted(
            r for r, hh in self.hosts.items() if hh.dead
        ))
        obs.metric_event(
            "distserve.host.died", host=h.rank, reason=h.dead_reason
        )
        self._degrade(f"host{h.rank}", reason)

    def mark_host_dead(self, rank: int, reason: str) -> None:
        with self._cond:
            self._mark_dead_locked(self.hosts[rank], reason)
            self._cond.notify_all()

    def _check_workers(self) -> None:
        respawn: list[int] = []
        with self._cond:
            for r, h in self.hosts.items():
                if h.live:
                    if h.proc is not None and not h.proc.is_alive():
                        self._mark_dead_locked(
                            h, "process exited (code "
                               f"{h.proc.exitcode}) without bye"
                        )
                    elif h.thread is not None and not h.thread.is_alive():
                        self._mark_dead_locked(h, "worker thread died")
                if (
                    h.dead
                    and h.dead_until is None
                    and self.dscfg.respawn
                    and not self._stop_req.is_set()
                ):
                    respawn.append(r)
            self._cond.notify_all()
        for r in respawn:
            self._spawn_host(r, rejoin=True)

    # -- merge + publication ----------------------------------------------
    def _expected(self, w: int) -> list[int]:
        """Hosts whose epoch for window ``w`` is still owed (lock held)."""
        out = []
        for r, h in self.hosts.items():
            if h.start_window > w or h.last_wid >= w:
                continue
            if h.finished or h.dead:
                continue
            out.append(r)
        return out

    def _dead_at(self, w: int) -> list[int]:
        """Hosts whose death swallowed window ``w`` (lock held)."""
        out = []
        for r, h in self.hosts.items():
            if h.dead_from is None or h.dead_from > w:
                continue
            if h.dead_until is not None and w >= h.dead_until:
                continue
            out.append(r)
        return out

    def _drain_publishable(self) -> None:
        while True:
            with self._lock:
                w = self.next_wid
                # a window no surviving host ever reached cannot publish:
                # skip it explicitly (accounted in /health + summary),
                # never hang the frontier behind it
                while (
                    self._pending
                    and w < min(self._pending)
                    and not self._expected(w)
                ):
                    self.skipped_windows.append(w)
                    obs.instant("serve.window.skipped", args={"window": w})
                    self.next_wid = w = w + 1
                recs = self._pending.get(w)
                if not recs:
                    break
                waiting = self._expected(w)
                timed_out = (
                    waiting
                    and time.monotonic() - self._arrival.get(w, 0.0)
                    > self.dscfg.merge_timeout_sec
                )
                alldone = all(
                    not h.live for h in self.hosts.values()
                )
                if waiting and not timed_out and not alldone:
                    break
                recs = self._pending.pop(w)
                self._arrival.pop(w, None)
                dead = [r for r in self._dead_at(w) if r not in recs]
                missing = [
                    r for r in waiting if r not in recs and r not in dead
                ]
                self.next_wid = w + 1
            self._publish_window(w, recs, dead, missing)

    def _publish_window(
        self,
        w: int,
        recs: dict[int, tuple[dict, dict]],
        dead: list[int],
        missing: list[int],
        *,
        path: str = "live",
    ) -> None:
        self._check_fenced()  # a stale supervisor must never publish
        ranks = sorted(recs)
        with obs.span("distserve.merge", window=w, hosts=len(ranks)):
            arrays = merge_register_arrays([recs[r][0] for r in ranks])
            # candidate-table merge law: the hosts saw DISJOINT slices
            # of the same window, so a source's per-host estimates ADD
            # (the CMS add law lifted to the candidate tables) — unlike
            # cross-WINDOW merges (cum_tracker, merged views), where
            # re-offering the same window's table must stay max/idempotent.
            # Summing is what keeps the merged talkers section
            # bit-identical to a single-host replay of the union.
            cand: dict[int, dict[int, int]] = {}
            quarantine: dict[tuple, int] = {}
            per_host: dict[str, dict] = {}
            reasons: list[str] = []
            partial = False
            lines = parsed = skipped = chunks = drops = 0
            started = ended = None
            elapsed = 0.0
            for r in ranks:
                _arr, extra = recs[r]
                meta = extra["meta"]
                per_host[str(r)] = meta
                lines += int(meta.get("lines", 0))
                parsed += int(meta.get("parsed", 0))
                skipped += int(meta.get("skipped", 0))
                chunks += int(meta.get("chunks", 0))
                drops += int(meta.get("drops", 0))
                partial = partial or bool(meta.get("partial"))
                elapsed = max(elapsed, float(meta.get("elapsed_sec", 0.0)))
                su, eu = meta.get("started_unix"), meta.get("ended_unix")
                started = su if started is None else min(started, su)
                ended = eu if ended is None else max(ended, eu)
                for reason in (meta.get("incomplete") or {}).get(
                    "reasons", []
                ):
                    if reason not in reasons:
                        reasons.append(reason)
                for acl, table in extra.get("tracker", []):
                    t = cand.setdefault(int(acl), {})
                    for src, est in table:
                        t[int(src)] = t.get(int(src), 0) + int(est)
                _merge_quarantine(
                    quarantine, _de_quarantine(extra.get("quarantine", []))
                )
                for d, s in extra.get("v6_digests", []):
                    self._v6_digests.setdefault(int(d), int(s))
            tracker = TopKTracker(self.cfg.sketch.topk_capacity)
            for acl in sorted(cand):
                # canonical offer order (estimate desc, source asc):
                # capacity eviction keeps the heaviest merged talkers
                # regardless of which host shipped its table first
                for src, est in sorted(
                    cand[acl].items(), key=lambda kv: (-kv[1], kv[0])
                ):
                    tracker.offer(acl, src, est)
            for r in sorted(dead):
                reasons.append(f"host_died:{r}")
            for r in sorted(missing):
                reasons.append(f"host_missing:{r}")
            meta = {
                "id": w,
                "term": self.term,  # which leadership published this
                "mode": "lines" if self.scfg.window_lines else "sec",
                "length": self.scfg.window_lines or self.scfg.window_sec,
                "lines": lines,
                "parsed": parsed,
                "skipped": skipped,
                "chunks": chunks,
                "drops": drops,
                "reloads": 0,
                "started_unix": started if started is not None else 0.0,
                "ended_unix": ended if ended is not None else 0.0,
                "elapsed_sec": round(elapsed, 4),
                "hosts": per_host,
                "merged_hosts": ranks,
            }
            if partial:
                meta["partial"] = True
            if reasons:
                meta["incomplete"] = {
                    "drops": drops,
                    "reasons": reasons,
                    **({"dead_hosts": sorted(dead)} if dead else {}),
                    **({"missing_hosts": sorted(missing)} if missing else {}),
                }
            ep = WindowEpoch(
                arrays=arrays,
                meta=meta,
                tracker_tables=tracker.tables(),
                quarantine=quarantine,
            )
            rep = pipeline.finalize(
                pipeline.AnalysisState(**arrays), self.packed, self.cfg,
                tracker, topk=self.topk,
                totals=self._window_totals(meta, quarantine),
                v6_digests=self._v6_digests,
            )
            rep_obj = json.loads(rep.to_json())
            if self.scfg.lineage:
                # the merged window's provenance (DESIGN §24): one entry
                # per contributing host with its delivered WAL range and
                # the crc of the exact epoch payload it shipped.  All of
                # it is a deterministic function of the delivered epochs
                # — only term/path/published_unix/crc (LINEAGE_VOLATILE)
                # may differ between a live publish and a failover
                # successor's replay of the same spooled bytes
                eff_path = path
                if eff_path == "live" and any(
                    recs[r][1].get("healed") for r in ranks
                ):
                    eff_path = "backlog_heal"
                lrec: dict = {
                    "window": w,
                    "kind": "dist",
                    "hosts": [{
                        "rank": int(r),
                        "wal_seq_lo": int(recs[r][1].get("wal_lo", 0)),
                        "wal_seq_hi": int(recs[r][1].get("wal_next", 0)),
                        "drops": int(
                            recs[r][1]["meta"].get("drops", 0)
                        ),
                        "quarantine_hits": int(sum(
                            int(row[-1])
                            for row in recs[r][1].get("quarantine", [])
                        )),
                        "payload_crc": int(
                            recs[r][1].get("payload_crc", 0)
                        ),
                    } for r in ranks],
                    "generation": int(self.reloads),
                    "term": int(self.term),
                    "path": eff_path,
                    "published_unix": round(time.time(), 3),
                }
                if dead:
                    lrec["dead_hosts"] = sorted(dead)
                if missing:
                    lrec["missing_hosts"] = sorted(missing)
                if meta.get("incomplete"):
                    lrec["incomplete"] = meta["incomplete"]
                rep_obj["totals"]["lineage"] = seal_lineage(lrec)
                # merged-K records sealed inside the borrowed _publish
                # carry the same path stamp
                self._path = eff_path
            if meta.get("incomplete"):
                self.cum_incomplete_windows.append(w)
                for r in meta["incomplete"]["reasons"]:
                    if r not in self.cum_incomplete_reasons:
                        self.cum_incomplete_reasons.append(r)
            with self._pub_lock:
                self.ring.push(ep)
                prev = self._published.get("report")
                _merge_quarantine(self.cum_quarantine, quarantine)
            self.cum_arrays = merge_register_arrays(
                [self.cum_arrays, arrays]
            )
            for acl, table in ep.tracker_tables.items():
                for src, est in table.items():
                    self.cum_tracker.offer(int(acl), int(src), int(est))
            self.total_lines += lines
            self.total_parsed += parsed
            self.total_skipped += skipped
            self.total_chunks += chunks
            self.live_drops += drops
            self.windows_published += 1
            with self._lock:
                for r in ranks:
                    h = self.hosts.get(r)
                    if h is not None:
                        h.wal_ckpt = max(
                            h.wal_ckpt,
                            int(recs[r][1].get("wal_next", 0)),
                        )
            # durable history spills the MERGED epoch (post cross-host
            # register merge) so range queries see exactly what /report
            # published, not any single host's shard
            self._spill_epoch(ep)
            if self._suffix is not None:
                self._suffix.push(w, arrays)
            flightrec.cursor(
                windows_published=self.windows_published,
                next_window=self.next_wid,
            )
            obs.metric_event(
                "distserve.window", id=w, hosts=len(ranks), lines=lines,
                drops=drops, dead=len(dead), missing=len(missing),
            )
            self._publish(rep_obj, prev, meta)
            self._path = "live"
            # burn-rate engine over the MERGED windows (rank 0 has no
            # per-window ingest->publish histogram, so latency
            # objectives are host-tier concerns; drop/incomplete/
            # degraded objectives burn here)
            self._observe_slo(meta)
            if (
                self.scfg.checkpoint_every_windows
                and self.windows_published
                % self.scfg.checkpoint_every_windows == 0
            ):
                self._save_ckpt()

    # -- the supervisor loop ----------------------------------------------
    def _merge_loop(self) -> None:
        while True:
            with self._cond:
                self._cond.wait(timeout=0.2)
            if self._sup_kill:
                raise AnalysisError(
                    "distserve supervisor killed (injected supervisor "
                    "death); pending epochs stay in the host spools for "
                    "the elected successor to replay"
                )
            self._check_fenced()
            self._check_workers()
            self._maybe_autoscale()
            if self._stop_req.is_set():
                # per-host (and per-generation) delivery, retried every
                # pass: a worker that comes up AFTER the stop request —
                # a respawn racing max_windows, an autoscale spawn — has
                # no channel yet when the request lands, and a one-shot
                # broadcast would leave it running forever (alldone
                # never true = supervisor hang)
                with self._lock:
                    pend = [
                        h for h in self.hosts.values()
                        if h.live and not h.stop_sent
                        and (h.driver is not None or h.conn is not None)
                    ]
                    for h in pend:
                        h.stop_sent = True
                for h in pend:
                    self._send_control(h, b"S")
            self._drain_publishable()
            if (
                self.scfg.max_windows
                and self.windows_published >= self.scfg.max_windows
                and not self._stop_req.is_set()
            ):
                # --max-windows is a SERVICE budget: it counts merged
                # published windows, exactly like the single-host
                # driver counts its own.  Workers inherit the budget as
                # a local backstop, but a host that joined at the merge
                # frontier (respawn, scale-out) publishes fewer LOCAL
                # windows than the service total and would never
                # self-stop — rank 0 must stop the world, or alldone
                # never comes
                self._stop_req.set()
            with self._lock:
                alldone = all(not h.live for h in self.hosts.values())
                empty = not self._pending
            if alldone:
                if not empty:
                    continue  # next pass publishes the tail
                break

    def _maybe_autoscale(self) -> None:
        eng = self._engine
        if eng is None:
            return
        now = time.monotonic()
        if now < self._as_next:
            return
        self._as_next = now + self.ascfg.poll_sec
        with self._lock:
            live = [h for h in self.hosts.values() if h.live]
            if not live or any(not h.gauges for h in live):
                return  # no full signal yet
            pressure = max(
                h.gauges.get("queue_depth", 0)
                / max(h.gauges.get("queue_capacity", 1), 1)
                for h in live
            )
            starvation = min(
                float(h.gauges.get("starved_frac", 0.0)) for h in live
            )
            world = len(live)
        if world in eng.ladder:
            # resync the rung to reality (a death can shrink the live
            # set under the engine); below the ladder floor the engine
            # keeps its last rung — respawn, not policy, owns recovery
            eng.world = world
        dec = eng.observe(
            now=now, pressure=pressure, starvation=starvation,
            gauges={"hosts_live": world, "pressure": round(pressure, 4)},
        )
        if dec is None or not dec.actuate:
            return
        with obs.span(
            "distserve.autoscale.apply", seq=dec.seq,
            direction=dec.direction, from_world=dec.from_world,
            to_world=dec.to_world,
        ):
            faults.fire("autoscale.spawn")
            if dec.direction == "out":
                with self._lock:
                    rank = max(self.hosts) + 1 if self.hosts else 0
                self._spawn_host(rank, rejoin=False)
            else:
                with self._lock:
                    live = sorted(
                        (r for r, h in self.hosts.items()
                         if h.live and not h.retiring),
                        reverse=True,
                    )
                    target = self.hosts[live[0]] if live else None
                    if target is not None:
                        target.retiring = True
                if target is not None:
                    self._send_control(target, b"R")
        eng.applied(dec, now=time.monotonic())
        obs.metric_event(
            "distserve.autoscale.applied", seq=dec.seq,
            direction=dec.direction, world=dec.to_world,
        )

    # -- process-mode merge server ----------------------------------------
    def _accept_loop(self) -> None:
        while not self._accept_stop:
            try:
                conn, _ = self._msock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._conn_reader, args=(conn,),
                name="ra-distserve-conn", daemon=True,
            ).start()

    def _conn_reader(self, conn: socket.socket) -> None:
        rank: int | None = None
        try:
            while True:
                fr = _recv_frame(conn)
                if fr is None:
                    break
                kind, body = fr
                if kind == b"H":
                    j = json.loads(body)
                    rank = int(j["rank"])
                    with self._lock:
                        h = self.hosts.get(rank)
                        if h is not None:
                            h.conn = conn
                    continue
                if rank is None:
                    raise AnalysisError(
                        "host-tier frame before hello; dropping connection"
                    )
                self._on_frame(rank, kind, body)
        except (OSError, AnalysisError, ValueError, KeyError) as e:
            if rank is not None:
                self.mark_host_dead(rank, f"merge connection error: {e}")
        finally:
            # EOF without a bye is a death signal in its own right (the
            # process monitor confirms with the exit code)
            if rank is not None:
                with self._cond:
                    h = self.hosts.get(rank)
                    if h is not None and h.live and h.conn is conn:
                        self._mark_dead_locked(
                            h, "merge connection closed without bye"
                        )
                        self._cond.notify_all()
            try:
                conn.close()
            except OSError:
                pass

    # -- checkpoint (rank-0 merged ring; ladder-max fingerprint) -----------
    def _save_ckpt(self) -> None:
        self._check_fenced()  # a fenced snapshot could roll back the
        # successor's frontier — refuse it like any other publication
        arrays: dict[str, np.ndarray] = {}
        wmeta = []
        for ep in self.ring.epochs:
            pfx = f"w{ep.meta['id']:06d}__"
            for k, v in ep.arrays.items():
                arrays[pfx + k] = v
            wmeta.append({
                "meta": ep.meta,
                "tracker": _ser_tracker(ep.tracker_tables),
                "quarantine": _ser_quarantine(ep.quarantine),
            })
        for k, v in self.cum_arrays.items():
            arrays["cum__" + k] = v
        with self._lock:
            host_wal = {
                str(r): int(h.wal_ckpt) for r, h in self.hosts.items()
            }
        snap = ckpt.Snapshot(
            arrays=arrays,
            lines_consumed=self.total_lines,
            n_chunks=self.total_chunks,
            parsed=self.total_parsed,
            skipped=self.total_skipped,
            tracker_tables=self.cum_tracker.tables(),
            # the fencing term rides the fingerprint as a -t<term>
            # suffix (ckpt.split_fence peels it): a restore that finds
            # a HIGHER term than its own lease proves a successor
            # already ran — SupervisorFenced, not a resume
            fingerprint=ckpt.fence_fingerprint(self._fp, self.term),
            extra={
                "serve": {
                    "next_window": self.next_wid,
                    "windows_published": self.windows_published,
                    "windows": wmeta,
                    "reloads": self.reloads,
                    "quarantine": _ser_quarantine(self.cum_quarantine),
                    "v6_digests": [
                        [int(d), int(s)]
                        for d, s in self._v6_digests.items()
                    ],
                    "incomplete_reasons": list(
                        self.cum_incomplete_reasons
                    ),
                    "incomplete_windows": list(
                        self.cum_incomplete_windows
                    ),
                    "drops": self.drops_restored + self.live_drops,
                    "wal_seq": 0,
                    "wal_lost": 0,
                },
                # per-host WAL cursors COVERED BY PUBLISHED WINDOWS
                # (not merely received: a pending-but-unpublished epoch
                # dies with this process, and its lines must replay)
                "distserve": {
                    "host_wal": host_wal,
                    "skipped_windows": list(self.skipped_windows),
                    "late_epochs": self.late_epochs,
                },
            },
        )
        try:
            ckpt.save(
                self.scfg.checkpoint_dir or os.path.join(
                    self.scfg.serve_dir, "ckpt"
                ),
                snap,
            )
        except (OSError, AnalysisError) as e:
            self._degrade("checkpoint", e)
            return
        self._recover("checkpoint")

    def _restore(self) -> None:
        snap = ckpt.load(
            self.scfg.checkpoint_dir
            or os.path.join(self.scfg.serve_dir, "ckpt")
        )
        if snap is None:
            return
        base_fp, snap_term = ckpt.split_fence(snap.fingerprint)
        if snap_term > self.term and self._lease is not None:
            t, h = self._lease.observed()
            raise SupervisorFenced(
                f"checkpoint was written by fencing term {snap_term} but "
                f"this supervisor holds term {self.term} (newest observed "
                f"leadership: term {t} by {h!r}); a successor already ran "
                "— refusing to roll its frontier back"
            )
        if base_fp != self._fp:
            raise ckpt.CheckpointMismatch(
                "distributed serve checkpoint was taken with a different "
                "ruleset, sketch geometry, or host-tier ladder maximum; "
                "refusing to resume the merged ring (delete the serve "
                "checkpoint dir, or keep --dist-max-hosts stable across "
                "restarts — the ladder max, not the live host count, is "
                "the resume identity)"
            )
        sv = (snap.extra or {}).get("serve")
        if not sv:
            raise ckpt.CheckpointCorrupt(
                "distributed serve checkpoint manifest lacks the serve "
                "extra block"
            )
        self.total_lines = snap.lines_consumed
        self.total_chunks = snap.n_chunks
        self.total_parsed = snap.parsed
        self.total_skipped = snap.skipped
        self.cum_tracker = ckpt.restore_tracker(
            snap, self.cfg.sketch.topk_capacity
        )
        self.cum_arrays = {
            k[len("cum__"):]: v
            for k, v in snap.arrays.items()
            if k.startswith("cum__")
        }
        self.next_wid = int(sv["next_window"])
        self.windows_published = int(sv.get("windows_published", 0))
        self.cum_quarantine = _de_quarantine(sv.get("quarantine", []))
        self._v6_digests.update(
            {int(d): int(s) for d, s in sv.get("v6_digests", [])}
        )
        self.cum_incomplete_reasons = list(sv.get("incomplete_reasons", []))
        self.cum_incomplete_windows = [
            int(w) for w in sv.get("incomplete_windows", [])
        ]
        self.drops_restored = int(sv.get("drops", 0))
        ds = (snap.extra or {}).get("distserve", {})
        self._host_wal_restored = {
            int(r): int(s) for r, s in ds.get("host_wal", {}).items()
        }
        self.skipped_windows = [
            int(w) for w in ds.get("skipped_windows", [])
        ]
        for wrec in sv.get("windows", []):
            meta = wrec["meta"]
            pfx = f"w{meta['id']:06d}__"
            self.ring.push(WindowEpoch(
                arrays={
                    k[len(pfx):]: v
                    for k, v in snap.arrays.items()
                    if k.startswith(pfx)
                },
                meta=meta,
                tracker_tables={
                    int(acl): {int(s): int(e) for s, e in t}
                    for acl, t in wrec.get("tracker", [])
                },
                quarantine=_de_quarantine(wrec.get("quarantine", [])),
            ))
        for ep in self.ring.epochs:
            self._window_reports[ep.meta["id"]] = self._render_window_obj(ep)
        if self.ring.epochs:
            self._published["report"] = self._window_reports[
                self.ring.epochs[-1].meta["id"]
            ]
            self._published["cumulative"] = json.loads(
                self._render_cumulative().to_json()
            )

    # -- failover replay (DESIGN §23) --------------------------------------
    def _scan_spool_ranks(self) -> list[int]:
        root = self._spool_root()
        ranks = []
        try:
            names = os.listdir(root)
        except OSError:
            return ranks
        for n in names:
            if n.startswith("host-"):
                try:
                    r = int(n[5:])
                except ValueError:
                    continue
                if os.path.isdir(self._host_spool_dir(r)):
                    ranks.append(r)
        return sorted(ranks)

    def _replay_spools(self) -> None:
        """Elected-successor takeover: replay every host's durable epoch
        spool past the restored merge frontier and publish those windows
        exactly as the dead supervisor would have — the merge laws are
        associative, so replay order is free and the output is
        bit-identical to the union (the tentpole invariant the failover
        chaos tests pin).

        Loss discipline mirrors the merge loop's: a window some host
        spooled later epochs past but not this one gets a typed
        ``host_missing:<rank>`` marker; a window NO host's spool reached
        is skipped with explicit accounting; a corrupt spooled epoch is
        refused typed by ``unpack_epoch_payload`` and counted — never a
        crash, never a silently wrong merge.
        """
        if self.dscfg.spool_budget_mb <= 0:
            return
        t0 = time.monotonic()
        frontier = self.next_wid
        pending: dict[int, dict[int, tuple[dict, dict]]] = {}
        top_by_host: dict[int, int] = {}
        epochs = 0
        for rank in self._scan_spool_ranks():
            try:
                spool = EpochSpool(
                    self._host_spool_dir(rank),
                    budget_bytes=self.dscfg.spool_budget_mb << 20,
                )
            except (WalQuarantine, OSError) as e:
                self._degrade(f"spool{rank}", e)
                continue
            try:
                for seq, payload in spool.replay(0):
                    try:
                        arrays, extra = unpack_epoch_payload(payload)
                        wid = int(extra["meta"]["id"])
                    except (AnalysisError, KeyError, TypeError, ValueError) as e:
                        self.replay_refused_total += 1
                        obs.instant("distserve.replay.refused", args={
                            "host": rank, "seq": seq,
                            "error": f"{type(e).__name__}: {e}"[:160],
                        })
                        continue
                    epochs += 1
                    # the spool holds the exact bytes the host shipped
                    # (or would have shipped), so this crc matches what
                    # the dead supervisor stamped at live arrival —
                    # lineage cores come out identical (replay-identity)
                    extra["payload_crc"] = zlib.crc32(payload) & 0xFFFFFFFF
                    top_by_host[rank] = max(top_by_host.get(rank, -1), wid)
                    # the replayed epoch's WAL cursor supersedes the
                    # checkpointed one: a rejoining host must not replay
                    # WAL lines a replayed window already covers (that
                    # would double-count them)
                    self._host_wal_restored[rank] = max(
                        self._host_wal_restored.get(rank, 0),
                        int(extra.get("wal_next", 0)),
                    )
                    if wid >= frontier:
                        pending.setdefault(wid, {})[rank] = (arrays, extra)
            finally:
                spool.close()
        self.spool_replayed_total = epochs
        self.replay_lag_windows = len(pending)
        for w in sorted(pending):
            while self.next_wid < w:
                # a window below every surviving spool record: all its
                # epochs are gone (evicted/quarantined) — skip loudly
                self.skipped_windows.append(self.next_wid)
                obs.instant("serve.window.skipped", args={
                    "window": self.next_wid, "replay": True,
                })
                self.next_wid += 1
            recs = pending[w]
            missing = sorted(
                r for r, top in top_by_host.items()
                if r not in recs and top > w
            )
            self.next_wid = w + 1
            self._publish_window(w, recs, [], missing, path="replay")
            self.replay_windows_total += 1
        obs.instant("distserve.failover.replay", args={
            "frontier": frontier,
            "epochs": epochs,
            "windows": self.replay_windows_total,
            "refused": self.replay_refused_total,
            "takeover_sec": round(time.monotonic() - t0, 3),
        })
        flightrec.cursor(
            replay_windows=self.replay_windows_total,
            next_window=self.next_wid,
        )

    # -- plumbing ----------------------------------------------------------
    def _start_http(self) -> None:
        if self._http is None:
            return
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, name="ra-distserve-http",
            daemon=True,
        )
        self._http_thread.start()

    def _install_signals(self) -> None:
        import signal

        if threading.current_thread() is not threading.main_thread():
            return
        # SIGINT/SIGTERM stop gracefully: workers drain their final
        # partial windows, the merge frontier publishes them, then
        # summary.json lands.  No SIGHUP reload in distributed v1
        # (restart the deployment to re-pack; DESIGN §22 scope bound).
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                self._old_signals[sig] = signal.signal(
                    sig, lambda *_: self.stop()
                )
            except (ValueError, OSError):
                pass

    def _teardown(self, aborted: BaseException | None) -> None:
        import signal

        self._stop_req.set()
        for sig, old in self._old_signals.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old_signals = {}
        with self._lock:
            live = [h for h in self.hosts.values() if h.live]
        for h in live:
            self._send_control(h, b"S")
        deadline = time.monotonic() + 30.0
        for h in list(self.hosts.values()):
            budget = max(deadline - time.monotonic(), 0.1)
            if h.thread is not None:
                h.thread.join(timeout=budget)
            if h.proc is not None:
                h.proc.join(timeout=budget)
                if h.proc.is_alive():
                    h.proc.terminate()
                    h.proc.join(timeout=5.0)
        self._accept_stop = True
        if self._msock is not None:
            try:
                self._msock.close()
            except OSError:
                pass
            if self._accept_thread is not None:
                self._accept_thread.join(timeout=5.0)
        if self._http is not None:
            if self._http_thread is not None:
                self._http.shutdown()
                self._http.server_close()
                self._http_thread.join(timeout=5.0)
            else:
                self._http.server_close()
        if self._lease is not None:
            # planned exit releases (clears the stamp so a successor
            # wins immediately); a fenced holder leaves lease.json to
            # the winner — release() knows the difference
            self._lease.release()
        if self._lineage_log is not None:
            self._lineage_log.sync()
            self._lineage_log.close()
            self._lineage_log = None
        if self.epoch_store is not None:
            self.epoch_store.sync()
            self.epoch_store.close()
        obs.unregister_sampler("distserve")
