"""Observability: throughput metering and optional device profiling.

The reference's only visibility is Hadoop's job counters and stdout
(SURVEY.md §6).  Here: a periodic stderr throughput line (lines/sec,
instantaneous and cumulative) and an opt-in ``jax.profiler`` trace whose
output loads in TensorBoard's profile plugin for per-op device timing.
"""

from __future__ import annotations

import sys
import time


class ThroughputMeter:
    """Periodic lines/sec reporting without per-chunk host/device syncs."""

    def __init__(self, report_every_chunks: int = 0, out=sys.stderr):
        self.every = report_every_chunks
        self.out = out
        self.t0 = time.perf_counter()
        self.t_last = self.t0
        self.lines = 0
        self.lines_last = 0
        self.chunks = 0

    def tick(self, n_lines: int) -> None:
        self.lines += n_lines
        self.chunks += 1
        if self.every and self.chunks % self.every == 0:
            now = time.perf_counter()
            inst = (self.lines - self.lines_last) / max(now - self.t_last, 1e-9)
            cum = self.lines / max(now - self.t0, 1e-9)
            print(
                f"[chunk {self.chunks}] {self.lines} lines, "
                f"{inst:,.0f} lines/s (inst), {cum:,.0f} lines/s (cum)",
                file=self.out,
                flush=True,
            )
            self.t_last, self.lines_last = now, self.lines

    def elapsed(self) -> float:
        return time.perf_counter() - self.t0


class DispatchTimer:
    """Prices one-time jit/XLA-compile apart from the sustained rate.

    The first dispatch of each device program blocks on trace + compile;
    its excess over the SECOND dispatch of the same program is the
    one-time cost.  (On a backend with synchronous dispatch — XLA:CPU —
    every dispatch also carries the chunk's execution, so
    first-minus-second isolates compile where raw first-dispatch time
    would launder one chunk's work into "compile".)  A program that
    dispatched only ONCE contributes ZERO: its lone timing conflates
    compile with a full chunk's execution, and subtracting it whole
    from the sustained denominator would inflate the sustained rate by
    10x+ on single-chunk runs — under-attributing compile there is the
    conservative error.  Shared by the single-process and distributed
    stream drivers so their ``totals.compile_sec`` mean the same thing.
    """

    def __init__(self):
        self._t: dict[str, list[float]] = {}

    def first(self, kind: str, fn, *args):
        """Run ``fn(*args)``, timing the first two dispatches of ``kind``."""
        lst = self._t.setdefault(kind, [])
        if len(lst) >= 2:
            return fn(*args)
        t0 = time.perf_counter()
        out = fn(*args)
        lst.append(time.perf_counter() - t0)
        return out

    def compile_sec(self) -> float:
        return sum(
            max(0.0, t[0] - t[1]) for t in self._t.values() if len(t) > 1
        )


class RecoveryMeter:
    """Recovery-event counters for the elastic supervisor (runtime/elastic.py).

    One event per cluster re-formation: ``detect()`` marks the moment a
    peer death (or any generation failure) is observed, ``recovered()``
    the moment the replacement generation's workers are running again.
    ``summary()`` feeds the end-of-run report totals, so an operator sees
    how often the job healed itself and how long each heal took — the
    observability half of the SURVEY §3b elastic/retry analog.
    """

    def __init__(self):
        self.events: list[dict] = []
        self._t_detect: float | None = None
        #: chaos-harness outcomes (record_run): one bool per seeded fault
        #: schedule — True when the run ended inside the invariant (bit-
        #: identical report or typed abort), False on any breach
        self.runs: list[bool] = []

    def detect(self, reason: str = "") -> None:
        if self._t_detect is None:  # first detection wins per event
            self._t_detect = time.perf_counter()
            self._reason = reason

    def recovered(self, *, world: int) -> None:
        t = time.perf_counter()
        t0 = self._t_detect if self._t_detect is not None else t
        self.events.append(
            {
                "time_to_recover_sec": round(t - t0, 3),
                "world": world,
                "reason": self._reason if self._t_detect is not None else "",
            }
        )
        self._t_detect = None

    def abandon(self) -> None:
        """Forget an open detection (budget exhausted: no recovery happened)."""
        self._t_detect = None

    def record_run(self, ok: bool) -> None:
        """One chaos schedule's verdict (pass-rate feeds BENCH artifacts)."""
        self.runs.append(bool(ok))

    def summary(self) -> dict:
        """Totals patch: {} when nothing was recorded (zero-noise)."""
        out: dict = {}
        if self.events:
            out.update(
                {
                    "recovery_events": len(self.events),
                    "recovery_total_sec": round(
                        sum(e["time_to_recover_sec"] for e in self.events), 3
                    ),
                    "mean_time_to_recover_sec": round(
                        sum(e["time_to_recover_sec"] for e in self.events)
                        / len(self.events),
                        3,
                    ),
                    "recoveries": self.events,
                }
            )
        if self.runs:
            # robustness the BENCH artifacts can track alongside speed:
            # how many seeded fault schedules ended inside the
            # bit-identical-or-typed-abort invariant
            out.update(
                {
                    "chaos_runs": len(self.runs),
                    "chaos_pass_rate": round(
                        sum(self.runs) / len(self.runs), 4
                    ),
                }
            )
        return out


class Profiler:
    """Context manager around jax.profiler tracing (no-op when dir is None)."""

    def __init__(self, trace_dir: str | None):
        self.trace_dir = trace_dir

    def __enter__(self):
        if self.trace_dir:
            import jax

            jax.profiler.start_trace(self.trace_dir)
        return self

    def __exit__(self, *exc):
        if self.trace_dir:
            import jax

            jax.profiler.stop_trace()
        return False
