"""Observability: throughput metering and optional device profiling.

The reference's only visibility is Hadoop's job counters and stdout
(SURVEY.md §6).  Here: a periodic stderr throughput line (lines/sec,
instantaneous and cumulative) and an opt-in ``jax.profiler`` trace whose
output loads in TensorBoard's profile plugin for per-op device timing.
"""

from __future__ import annotations

import sys
import time


class ThroughputMeter:
    """Periodic lines/sec reporting without per-chunk host/device syncs."""

    def __init__(self, report_every_chunks: int = 0, out=sys.stderr):
        self.every = report_every_chunks
        self.out = out
        self.t0 = time.perf_counter()
        self.t_last = self.t0
        self.lines = 0
        self.lines_last = 0
        self.chunks = 0

    def tick(self, n_lines: int) -> None:
        self.lines += n_lines
        self.chunks += 1
        if self.every and self.chunks % self.every == 0:
            now = time.perf_counter()
            inst = (self.lines - self.lines_last) / max(now - self.t_last, 1e-9)
            cum = self.lines / max(now - self.t0, 1e-9)
            print(
                f"[chunk {self.chunks}] {self.lines} lines, "
                f"{inst:,.0f} lines/s (inst), {cum:,.0f} lines/s (cum)",
                file=self.out,
                flush=True,
            )
            self.t_last, self.lines_last = now, self.lines

    def elapsed(self) -> float:
        return time.perf_counter() - self.t0


class Profiler:
    """Context manager around jax.profiler tracing (no-op when dir is None)."""

    def __init__(self, trace_dir: str | None):
        self.trace_dir = trace_dir

    def __enter__(self):
        if self.trace_dir:
            import jax

            jax.profiler.start_trace(self.trace_dir)
        return self

    def __exit__(self, *exc):
        if self.trace_dir:
            import jax

            jax.profiler.stop_trace()
        return False
