"""Observability meters: throughput, dispatch timing, recovery, profiling.

The reference's only visibility is Hadoop's job counters and stdout
(SURVEY.md §6).  Here: a periodic throughput line (lines/sec,
instantaneous and cumulative), the compile-vs-sustained dispatch timer,
recovery-event accounting, and an opt-in ``jax.profiler`` trace whose
output loads in TensorBoard's profile plugin for per-op device timing.
Every meter also feeds the unified tracing + metrics plane
(``runtime/obs.py``) when it is armed — spans for device dispatches and
elastic re-formations, line counters and throughput events for the
metrics JSONL — at a disarmed cost of one None-check per site.
"""

from __future__ import annotations

import math
import sys
import threading
import time

from . import obs

# ---------------------------------------------------------------------------
# Fixed-bucket latency histograms (DESIGN §20).  Log2 bucket bounds with
# u64 counts: mergeable across processes/windows by plain addition (the
# same merge-law discipline as the device registers — associative,
# commutative, order-free), so a fleet's histograms sum into one without
# any resampling.  One schema everywhere: report ``totals.latency``,
# metrics JSONL snapshots, and serve ``/metrics`` in BOTH the JSON gauge
# form (p50/p90/p99) and the Prometheus histogram exposition
# (``_bucket``/``_sum``/``_count`` with cumulative ``le`` labels).
# ---------------------------------------------------------------------------

#: Upper bucket bounds in seconds: 1 µs * 2^i for i in 0..33 (~2.4 h),
#: plus an implicit +Inf overflow bucket.  Fixed for every histogram so
#: counts merge positionally.
LATENCY_BUCKET_BOUNDS: tuple[float, ...] = tuple(
    (1 << i) * 1e-6 for i in range(34)
)


class LatencyHistogram:
    """Log2-bucket latency histogram with u64 counts.

    ``record`` is O(1) (a bit_length + one increment under a short
    lock); quantiles are conservative — they report the UPPER bound of
    the bucket containing the target rank, so a published p99 is always
    >= the true p99 (never a flattering under-estimate).  Samples
    landing past the last finite bound count in the overflow bucket and
    clamp quantiles to the largest finite bound.
    """

    N = len(LATENCY_BUCKET_BOUNDS)

    def __init__(self):
        self.counts: list[int] = [0] * (self.N + 1)  # +1 = +Inf overflow
        self.sum_sec = 0.0
        self.count = 0
        self._lock = threading.Lock()

    @staticmethod
    def bucket_index(sec: float) -> int:
        """Smallest i with bounds[i] >= sec (N = the +Inf overflow)."""
        if sec <= 1e-6:
            return 0
        us = int(math.ceil(sec * 1e6))
        i = (us - 1).bit_length()
        return min(i, LatencyHistogram.N)

    def record(self, sec: float, n: int = 1) -> None:
        """Add ``n`` samples of ``sec`` (n > 1 = decimated sampling)."""
        if sec < 0:
            sec = 0.0  # monotonic sources cannot go negative; belt+braces
        i = self.bucket_index(sec)
        with self._lock:
            self.counts[i] += n
            self.sum_sec += sec * n
            self.count += n

    def merge(self, other: "LatencyHistogram") -> None:
        """Positional count addition — the histogram merge law."""
        with other._lock:
            counts = list(other.counts)
            s, c = other.sum_sec, other.count
        with self._lock:
            for i, v in enumerate(counts):
                self.counts[i] += v
            self.sum_sec += s
            self.count += c

    def _quantile_locked(self, p: float) -> float:
        if self.count == 0:
            return 0.0
        rank = max(1, math.ceil(p * self.count))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                return LATENCY_BUCKET_BOUNDS[min(i, self.N - 1)]
        return LATENCY_BUCKET_BOUNDS[-1]

    def quantile(self, p: float) -> float:
        with self._lock:
            return self._quantile_locked(p)

    def summary(self) -> dict:
        """Report/totals image: counts + the SLO percentiles."""
        with self._lock:
            return {
                "count": self.count,
                "sum_sec": round(self.sum_sec, 6),
                "p50_sec": self._quantile_locked(0.50),
                "p90_sec": self._quantile_locked(0.90),
                "p99_sec": self._quantile_locked(0.99),
            }

    def gauges(self, prefix: str) -> dict:
        """Flat numeric gauges (serve /metrics JSON + prom gauge render)."""
        s = self.summary()
        return {f"{prefix}{k}": v for k, v in s.items()}

    def render_prom(self, name: str, labels: dict | None = None) -> str:
        """Prometheus histogram exposition (text format 0.0.4).

        Cumulative ``le`` buckets ending at ``+Inf``, plus ``_sum`` and
        ``_count`` — derived from the SAME counts as :meth:`summary`,
        so a scraper's bucket-derived p99 equals the JSON gauge exactly.
        ``labels`` (e.g. ``{"tenant": "acme"}``) prefix the ``le`` label
        on every bucket and brace the ``_sum``/``_count`` series — the
        multi-tenant serve /metrics renders one labeled histogram per
        tenant this way, and the labeled parity audit replays them
        through :func:`quantile_from_prom` with the same labels.
        """
        with self._lock:
            counts = list(self.counts)
            total = self.count
            sum_sec = self.sum_sec
        lab = _prom_labels(labels)
        pre = f"{lab}," if lab else ""
        suf = f"{{{lab}}}" if lab else ""
        lines = [f"# TYPE {name} histogram"]
        cum = 0
        for i, bound in enumerate(LATENCY_BUCKET_BOUNDS):
            cum += counts[i]
            # repr round-trips exactly: a scraper re-parsing the le label
            # recovers the identical float bound the JSON quantiles use
            lines.append(f'{name}_bucket{{{pre}le="{bound!r}"}} {cum}')
        lines.append(f'{name}_bucket{{{pre}le="+Inf"}} {total}')
        lines.append(f"{name}_sum{suf} {sum_sec:.9g}")
        lines.append(f"{name}_count{suf} {total}")
        return "\n".join(lines) + "\n"


def _prom_labels(labels: dict | None) -> str:
    """``k="v"`` label-pair body (no braces), sorted for determinism."""
    if not labels:
        return ""
    return ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))


def quantile_from_prom(
    text: str, name: str, p: float, labels: dict | None = None
) -> float | None:
    """p-quantile from a Prometheus histogram exposition (tests/audit).

    Same conservative bucket-upper-bound rule as
    :meth:`LatencyHistogram.quantile`, so the prom and JSON renderings
    of one histogram must agree exactly — the drift check
    ``verify/registry.py::audit_observability`` enforces.  ``labels``
    selects one labeled series out of a multi-tenant exposition (must
    match the ``render_prom(labels=...)`` that produced it).
    """
    lab = _prom_labels(labels)
    bucket_pre = f'{name}_bucket{{{lab},le="' if lab else f'{name}_bucket{{le="'
    count_pre = f"{name}_count{{{lab}}} " if lab else f"{name}_count "
    buckets: list[tuple[float, int]] = []
    count = None
    for line in text.splitlines():
        if line.startswith(bucket_pre):
            le, _, cum = line[len(bucket_pre):].partition('"} ')
            buckets.append(
                (math.inf if le == "+Inf" else float(le), int(cum))
            )
        elif line.startswith(count_pre):
            count = int(line.rsplit(" ", 1)[1])
    if count is None or not buckets:
        return None
    if count == 0:
        return 0.0
    rank = max(1, math.ceil(p * count))
    finite = [b for b, _ in buckets if b != math.inf]
    for bound, cum in buckets:
        if cum >= rank:
            return min(bound, finite[-1]) if finite else bound
    return finite[-1] if finite else None


class ThroughputMeter:
    """Periodic lines/sec reporting without per-chunk host/device syncs.

    Every tick also feeds the metrics plane's cumulative line counter
    (one None-check when ``--metrics-out`` is unset), and the periodic
    report line lands in the metrics JSONL as a ``throughput`` event in
    addition to stderr — a sustained run is watchable by tailing the
    metrics file instead of scraping stderr.  :meth:`summary` folds the
    final cumulative numbers into the report totals so downstream
    artifacts stop re-deriving them.
    """

    def __init__(self, report_every_chunks: int = 0, out=sys.stderr):
        self.every = report_every_chunks
        self.out = out
        self.t0 = time.perf_counter()
        self.t_last = self.t0
        self.lines = 0
        self.lines_last = 0
        self.chunks = 0

    def tick(self, n_lines: int) -> None:
        self.lines += n_lines
        self.chunks += 1
        obs.add_lines(n_lines)
        if self.every and self.chunks % self.every == 0:
            now = time.perf_counter()
            inst = (self.lines - self.lines_last) / max(now - self.t_last, 1e-9)
            cum = self.lines / max(now - self.t0, 1e-9)
            print(
                f"[chunk {self.chunks}] {self.lines} lines, "
                f"{inst:,.0f} lines/s (inst), {cum:,.0f} lines/s (cum)",
                file=self.out,
                flush=True,
            )
            obs.metric_event(
                "throughput",
                chunk=self.chunks,
                lines=self.lines,
                lines_per_sec_inst=round(inst, 1),
                lines_per_sec_cum=round(cum, 1),
            )
            self.t_last, self.lines_last = now, self.lines

    def elapsed(self) -> float:
        return time.perf_counter() - self.t0

    def summary(self) -> dict:
        """Final cumulative numbers for the report totals (``throughput``)."""
        elapsed = self.elapsed()
        return {
            "chunks_ticked": self.chunks,
            "lines": self.lines,
            "elapsed_sec": round(elapsed, 4),
            "lines_per_sec_cum": (
                round(self.lines / elapsed, 1) if elapsed > 0 else 0.0
            ),
        }


class DispatchTimer:
    """Prices one-time jit/XLA-compile apart from the sustained rate.

    The first dispatch of each device program blocks on trace + compile;
    its excess over the SECOND dispatch of the same program is the
    one-time cost.  (On a backend with synchronous dispatch — XLA:CPU —
    every dispatch also carries the chunk's execution, so
    first-minus-second isolates compile where raw first-dispatch time
    would launder one chunk's work into "compile".)  A program that
    dispatched only ONCE contributes ZERO: its lone timing conflates
    compile with a full chunk's execution, and subtracting it whole
    from the sustained denominator would inflate the sustained rate by
    10x+ on single-chunk runs — under-attributing compile there is the
    conservative error.  Shared by the single-process and distributed
    stream drivers so their ``totals.compile_sec`` mean the same thing.
    """

    def __init__(self):
        self._t: dict[str, list[float]] = {}

    def first(self, kind: str, fn, *args):
        """Run ``fn(*args)``, timing the first two dispatches of ``kind``.

        Every dispatch also records a ``step.dispatch`` trace span when
        the observability plane is armed — this method already wraps
        every device dispatch of both stream drivers, so one hook here
        covers the whole step taxonomy.  Disarmed cost past the first
        two dispatches: one None-check.
        """
        lst = self._t.setdefault(kind, [])
        rec = obs.recording()  # tracer shard OR flight-recorder ring
        if len(lst) >= 2 and not rec:
            return fn(*args)
        t0 = time.perf_counter()
        out = fn(*args)
        t1 = time.perf_counter()
        if len(lst) < 2:
            lst.append(t1 - t0)
        if rec:
            obs.complete(
                "step.dispatch", t0, t1, cat="step", args={"kind": kind}
            )
        return out

    def compile_sec(self) -> float:
        return sum(
            max(0.0, t[0] - t[1]) for t in self._t.values() if len(t) > 1
        )


class RecoveryMeter:
    """Recovery-event counters for the elastic supervisor (runtime/elastic.py).

    One event per cluster re-formation: ``detect()`` marks the moment a
    peer death (or any generation failure) is observed, ``recovered()``
    the moment the replacement generation's workers are running again.
    ``summary()`` feeds the end-of-run report totals, so an operator sees
    how often the job healed itself and how long each heal took — the
    observability half of the SURVEY §3b elastic/retry analog.
    """

    def __init__(self):
        self.events: list[dict] = []
        self._t_detect: float | None = None
        #: detection reason for the OPEN event; initialized here so an
        #: out-of-order recovered() (no prior detect()) reads a defined
        #: value instead of depending on attribute-existence luck
        self._reason: str = ""
        #: chaos-harness outcomes (record_run): one bool per seeded fault
        #: schedule — True when the run ended inside the invariant (bit-
        #: identical report or typed abort), False on any breach
        self.runs: list[bool] = []

    @property
    def detecting(self) -> bool:
        """True while a detected failure awaits its recovered() close.

        The elastic supervisor uses this to record a recovery event only
        for FAILURE re-formations — a planned autoscale re-formation has
        no detection window, and a zero-length recovery event would
        pollute the mean-time-to-recover statistics.
        """
        return self._t_detect is not None

    def detect(self, reason: str = "") -> None:
        if self._t_detect is None:  # first detection wins per event
            self._t_detect = time.perf_counter()
            self._reason = reason
            obs.instant("elastic.detect", args={"reason": reason})

    def recovered(self, *, world: int) -> None:
        t = time.perf_counter()
        t0 = self._t_detect if self._t_detect is not None else t
        event = {
            "time_to_recover_sec": round(t - t0, 3),
            "world": world,
            "reason": self._reason if self._t_detect is not None else "",
        }
        self.events.append(event)
        # the detect..recovered window IS the re-formation span; pushed
        # to both planes so a 10s recovery is visible on the timeline
        # and in the metrics JSONL without waiting for the final report
        obs.complete("elastic.reform", t0, t, cat="elastic", args=event)
        obs.metric_event("recovery", **event)
        self._t_detect = None

    def abandon(self) -> None:
        """Forget an open detection (budget exhausted: no recovery happened)."""
        self._t_detect = None

    def record_run(self, ok: bool) -> None:
        """One chaos schedule's verdict (pass-rate feeds BENCH artifacts)."""
        self.runs.append(bool(ok))

    def summary(self) -> dict:
        """Totals patch: {} when nothing was recorded (zero-noise)."""
        out: dict = {}
        if self.events:
            out.update(
                {
                    "recovery_events": len(self.events),
                    "recovery_total_sec": round(
                        sum(e["time_to_recover_sec"] for e in self.events), 3
                    ),
                    "mean_time_to_recover_sec": round(
                        sum(e["time_to_recover_sec"] for e in self.events)
                        / len(self.events),
                        3,
                    ),
                    "recoveries": self.events,
                }
            )
        if self.runs:
            # robustness the BENCH artifacts can track alongside speed:
            # how many seeded fault schedules ended inside the
            # bit-identical-or-typed-abort invariant
            out.update(
                {
                    "chaos_runs": len(self.runs),
                    "chaos_pass_rate": round(
                        sum(self.runs) / len(self.runs), 4
                    ),
                }
            )
        return out


class Profiler:
    """Context manager around jax.profiler tracing (no-op when dir is None).

    Hardened: entering twice is a typed error (jax's second start_trace
    would otherwise fail deep inside the profiler with an opaque
    message), the trace ALWAYS stops when the body raises (a stop_trace
    failure during exception unwind is swallowed so it cannot mask the
    run's real error), and a successful exit prints the trace path with
    the TensorBoard hint so operators do not have to know the plugin
    incantation.
    """

    def __init__(self, trace_dir: str | None, out=sys.stderr):
        self.trace_dir = trace_dir
        self.out = out
        self._active = False

    def __enter__(self):
        if self._active:
            from ..errors import AnalysisError

            raise AnalysisError(
                "Profiler already started; nest runs, not profiler scopes"
            )
        if self.trace_dir:
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self._active = True
        return self

    def __exit__(self, exc_type, exc, tb):
        if not self._active:
            return False
        self._active = False
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:
            # unwinding with the body's exception: the profiler's own
            # teardown failure must not mask it.  A clean-exit failure
            # is real and propagates.
            if exc_type is None:
                raise
        else:
            if exc_type is None:
                print(
                    f"profiler trace: {self.trace_dir} (open with "
                    "`tensorboard --logdir` -> Profile tab)",
                    file=self.out,
                    flush=True,
                )
        return False


# ---------------------------------------------------------------------------
# SLO burn-rate engine (DESIGN §24).  Policy objectives are evaluated per
# published window against the same log2 latency histograms and
# drop/incomplete/degraded counters the serve drivers already keep —
# no second measurement path, so the alert and the evidence can never
# disagree.  Fast/slow window pairs in the Google SRE style: the fast
# deque catches a sharp regression within a few rotations, the slow
# deque confirms sustained budget burn, and breach/recover fire only on
# state TRANSITIONS (hysteresis), never per-window, so a steady bad or
# steady good service emits nothing.
# ---------------------------------------------------------------------------

#: Window-stat keys an ``--slo`` objective may bound.  Latency quantiles
#: come from the per-window ingest->publish histogram (milliseconds);
#: the rates are per-window fractions in [0, 1]; ``degraded_subsystems``
#: is the live degraded-set size at rotation.
SLO_METRICS: tuple[str, ...] = (
    "p50_publish_ms",
    "p90_publish_ms",
    "p99_publish_ms",
    "drop_rate",
    "incomplete_rate",
    "degraded_subsystems",
)

_SLO_OBJ_RE = None  # compiled lazily; objective grammar: metric<=number


class SloPolicy:
    """Parsed ``--slo`` policy: a list of ``(metric, bound)`` objectives.

    Grammar (one comma-separated spec, whitespace-tolerant)::

        p99_publish_ms<=500,drop_rate<=0.001

    Only ``<=`` bounds: every supported metric is a "smaller is better"
    quantity, so one comparator keeps the spec unambiguous.  Unknown
    metric names are a hard :class:`ValueError` at parse time (config
    validation), never a silently-ignored objective at runtime.
    """

    def __init__(self, objectives: list[tuple[str, float]]):
        self.objectives = list(objectives)

    @classmethod
    def parse(cls, spec: str) -> "SloPolicy":
        import re

        global _SLO_OBJ_RE
        if _SLO_OBJ_RE is None:
            _SLO_OBJ_RE = re.compile(
                r"^\s*([a-z0-9_]+)\s*<=\s*([0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?)\s*$"
            )
        objectives: list[tuple[str, float]] = []
        seen: set[str] = set()
        for part in str(spec).split(","):
            if not part.strip():
                continue
            m = _SLO_OBJ_RE.match(part)
            if m is None:
                raise ValueError(
                    f"bad --slo objective {part.strip()!r} "
                    "(want metric<=number, e.g. p99_publish_ms<=500)"
                )
            metric, bound = m.group(1), float(m.group(2))
            if metric not in SLO_METRICS:
                raise ValueError(
                    f"unknown --slo metric {metric!r} "
                    f"(supported: {', '.join(SLO_METRICS)})"
                )
            if metric in seen:
                raise ValueError(f"duplicate --slo metric {metric!r}")
            seen.add(metric)
            objectives.append((metric, bound))
        if not objectives:
            raise ValueError("empty --slo spec")
        return cls(objectives)


class SloBurnEngine:
    """Multi-window burn-rate evaluator over per-window SLO stats.

    Each objective keeps two sliding windows of per-rotation compliance
    bits: ``fast`` (default 3 rotations) and ``slow`` (default 12).
    Burn rate = violating fraction / error budget; an objective BREACHES
    when the fast burn crosses ``fast_burn`` AND the slow burn crosses
    1.0 (budget fully consumed at the slow horizon), and RECOVERS once
    the fast burn falls back under 1.0 — i.e. the whole fast window is
    clean again.  The asymmetric pair is the hysteresis: one bad window
    alerts within ``fast`` rotations, and recovery needs ``fast``
    consecutive clean rotations, so the state cannot flap per-window.
    ``observe`` returns transition events only; gauges stay flat numeric
    so :func:`autoscale.render_prom` exports them with JSON<->prom
    parity for free.
    """

    def __init__(
        self,
        policy: SloPolicy,
        *,
        fast: int = 3,
        slow: int = 12,
        budget: float = 0.01,
        fast_burn: float = 2.0,
    ):
        if fast < 1 or slow < fast:
            raise ValueError("want 1 <= fast <= slow")
        self.policy = policy
        self.fast = int(fast)
        self.slow = int(slow)
        self.budget = float(budget)
        self.fast_burn = float(fast_burn)
        # per-objective: compliance-bit deque (1 = violated), breached flag
        self._bits: dict[str, list[int]] = {m: [] for m, _ in policy.objectives}
        self._breached: dict[str, bool] = {m: False for m, _ in policy.objectives}
        self._burn: dict[str, tuple[float, float]] = {
            m: (0.0, 0.0) for m, _ in policy.objectives
        }
        self.windows_observed = 0
        self.breaches_total = 0
        self.recoveries_total = 0

    def _burn_of(self, bits: list[int], horizon: int) -> float:
        tail = bits[-horizon:]
        if not tail:
            return 0.0
        return (sum(tail) / len(tail)) / self.budget

    def observe(self, stats: dict) -> list[dict]:
        """Feed one published window's stats; return transition events.

        Missing stat keys count as compliant (a window with no latency
        samples cannot violate a latency objective).  Events carry the
        objective, bound, observed value, and both burn rates — enough
        for the obs instant / flight-recorder record to stand alone.
        """
        self.windows_observed += 1
        events: list[dict] = []
        for metric, bound in self.policy.objectives:
            val = stats.get(metric)
            violated = 1 if (val is not None and float(val) > bound) else 0
            bits = self._bits[metric]
            bits.append(violated)
            del bits[:-self.slow]
            bf = self._burn_of(bits, self.fast)
            bs = self._burn_of(bits, self.slow)
            self._burn[metric] = (bf, bs)
            was = self._breached[metric]
            ev = None
            if not was and bf >= self.fast_burn and bs >= 1.0:
                self._breached[metric] = True
                self.breaches_total += 1
                ev = "slo.breach"
            elif was and bf < 1.0:
                self._breached[metric] = False
                self.recoveries_total += 1
                ev = "slo.recovered"
            if ev is not None:
                events.append({
                    "event": ev,
                    "objective": metric,
                    "bound": bound,
                    "value": None if val is None else float(val),
                    "burn_fast": round(bf, 4),
                    "burn_slow": round(bs, 4),
                    "window": stats.get("window"),
                })
        return events

    def gauges(self) -> dict:
        """Flat numeric gauges for the driver ``metrics_gauges`` merge."""
        g = {
            "slo_objectives": len(self.policy.objectives),
            "slo_windows_observed": self.windows_observed,
            "slo_breached": sum(1 for b in self._breached.values() if b),
            "slo_breaches_total": self.breaches_total,
            "slo_recoveries_total": self.recoveries_total,
        }
        return g

    def labeled_gauges(self) -> dict[str, dict]:
        """Per-objective gauge dicts for the labeled prom exposition."""
        out: dict[str, dict] = {}
        for metric, bound in self.policy.objectives:
            bf, bs = self._burn[metric]
            out[metric] = {
                "slo_bound": float(bound),
                "slo_burn_fast": round(bf, 4),
                "slo_burn_slow": round(bs, 4),
                "slo_objective_breached": 1 if self._breached[metric] else 0,
            }
        return out


def window_slo_stats(
    hist: "LatencyHistogram | None",
    *,
    lines: int,
    drops: int,
    incomplete: bool,
    degraded: int,
    window: int | None = None,
) -> dict:
    """One published window's stats in the shape ``SloBurnEngine.observe``
    and the lineage plane share.  Centralised so solo, tenant, and
    distributed serve cannot diverge on what "drop rate" means: drops
    over (delivered lines + drops), i.e. the fraction of offered lines
    the window lost."""
    stats: dict = {
        "drop_rate": (drops / (lines + drops)) if (lines + drops) > 0 else 0.0,
        "incomplete_rate": 1.0 if incomplete else 0.0,
        "degraded_subsystems": int(degraded),
        "window": window,
    }
    if hist is not None and hist.count > 0:
        for p, key in ((0.5, "p50_publish_ms"), (0.9, "p90_publish_ms"),
                       (0.99, "p99_publish_ms")):
            q = hist.quantile(p)
            if q == q and q != float("inf"):  # not NaN / overflow bucket
                stats[key] = q * 1e3
    return stats


# ---------------------------------------------------------------------------
# Build-info gauge (ra_build_info): the scrape-side answer to "what
# binary produced these numbers".  Constant-per-process labels (version,
# jax version, SIMD kind, mesh topology) with a value of 1, the standard
# Prometheus build-info idiom; the JSON /metrics variant carries the
# same dict verbatim and verify/registry.py::audit_observability holds
# the two renderings to each other.
# ---------------------------------------------------------------------------


def build_info(extra: dict | None = None) -> dict:
    """Assemble the build-info label dict (all values coerced to str)."""
    from .. import __version__

    try:
        import jax

        jax_version = str(jax.__version__)
    except Exception:  # pragma: no cover - jax is baked into the image
        jax_version = "unknown"
    try:
        from ..hostside import fastparse

        simd = str(fastparse.simd_kind())
    except Exception:  # pragma: no cover - fastparse probe never raises
        simd = "unknown"
    info = {"version": str(__version__), "jax": jax_version, "simd": simd}
    for k, v in (extra or {}).items():
        info[str(k)] = str(v)
    return info


def render_build_info_prom(info: dict, *, name: str = "ra_build_info") -> str:
    """One ``ra_build_info{...} 1`` line from :func:`build_info`'s dict."""
    body = _prom_labels({k: str(info[k]) for k in info})
    return f"# TYPE {name} gauge\n{name}{{{body}}} 1\n"
