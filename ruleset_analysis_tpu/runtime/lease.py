"""Supervisor lease + durable epoch spool for distributed serve failover.

PR 17's multi-host serve (DESIGN §22) made rank 0 the sole merge and
publication supervisor: one process death or partition silently ended
publication for the whole fleet even though every ingest tier stayed
healthy.  This module supplies the two primitives that kill that SPOF
(DESIGN §23):

- :class:`SupervisorLease` — a filesystem lease with a monotonically
  increasing **fencing term**.  Exactly-one-winner-per-term is a POSIX
  construction, not a protocol: claiming term ``N`` means creating
  ``term-<N>.claim`` with ``O_CREAT | O_EXCL``, which at most one
  process can ever succeed at.  The holder heartbeats ``lease.json``
  (atomic write-then-rename); it **self-fences** — reports
  ``fenced=True`` so the publication plane aborts typed — as soon as
  its renewals have been failing longer than the TTL, while a successor
  steals only after observing staleness **1.5x** the TTL.  Under the
  one-filesystem-clock assumption (the lease dir lives on one
  filesystem whose writers share a clock domain, true for the
  single-machine multi-process topology this repo exercises), the stale
  holder therefore provably stops publishing BEFORE any successor can
  win: split brain cannot produce two publications for one window id.

- :class:`EpochSpool` — a durable per-host spool of RAEP1 window-epoch
  frames, inheriting the WAL discipline wholesale from
  :class:`runtime.wal.WriteAheadLog` (O_APPEND framing, seq-gap = exact
  loss accounting, typed quarantine on damage, budget eviction counted
  never silent).  Every epoch a host ships to the supervisor is spooled
  FIRST, so a window epoch survives both its producer and any
  supervisor; an elected successor replays all spools past the fenced
  merge frontier and publishes bit-identically (the register merge laws
  are associative, so replay order is free).

Chaos seams (runtime/faults.py): ``lease.acquire`` (claim fails at
startup — typed abort before any host spawns), ``lease.renew`` (the
heartbeat dies and stays dead, the partition/storage-freeze analog —
the holder must self-fence within the TTL), ``dist.epoch.spool``
(append fails — the host degrades the spool subsystem but keeps
serving).  Unit-pinned in tests/test_failover.py without device work.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

from ..errors import StallError, WalQuarantine
from . import faults
from .wal import WriteAheadLog

LEASE_FILE = "lease.json"
#: a successor steals only after observing this much staleness, in TTLs;
#: the holder self-fences at 1.0 TTL, so the 0.5-TTL margin is what
#: makes "stale holder stops publishing before a successor can win" a
#: timing theorem rather than a race (DESIGN §23)
STEAL_FACTOR = 1.5

#: epoch-spool segment magic (8 bytes, like the WAL's): payload records
#: are whole RAEP1 frames, one window epoch each
SPOOL_MAGIC = b"RASPOOL1"
#: a window epoch (meta JSON + npz of the register planes) is MBs, not
#: syslog-line sized; anything past this bound is broken framing
MAX_EPOCH_BYTES = 256 << 20


def _atomic_write_json(path: str, obj) -> None:
    """fsync'd write-then-rename (the elastic rendezvous idiom)."""
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
    with os.fdopen(fd, "w", encoding="utf-8") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _claim_name(term: int) -> str:
    return f"term-{term:020d}.claim"


class SupervisorLease:
    """One supervisor's handle on the publication lease.

    Lifecycle: :meth:`acquire` blocks until this process wins a term,
    then a daemon heartbeat thread renews every ``ttl/4``; the
    publication plane consults :attr:`fenced` before every externally
    visible effect (publish, checkpoint) and raises
    ``SupervisorFenced`` when it reports True.  :meth:`release` stops
    the heartbeat and deletes ``lease.json`` so a planned handoff does
    not cost the successor the staleness wait.
    """

    def __init__(self, lease_dir: str, holder: str, ttl_sec: float):
        self.dir = os.path.abspath(lease_dir)
        self.holder = holder
        self.ttl = float(ttl_sec)
        try:
            os.makedirs(self.dir, exist_ok=True)
        except OSError as e:
            raise WalQuarantine(
                f"cannot create lease directory {lease_dir!r}: {e}"
            ) from e
        self.term = 0
        self.renews = 0
        self._observed_fence = False  # saw a claim for a HIGHER term
        self._last_renew = time.monotonic()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._on_fenced = None

    # -- on-disk state ----------------------------------------------------
    def _scan_top_claim(self) -> int:
        """Highest term anyone has ever claimed (0 = never claimed)."""
        top = 0
        try:
            for n in os.listdir(self.dir):
                if n.startswith("term-") and n.endswith(".claim"):
                    try:
                        top = max(top, int(n[5:-6]))
                    except ValueError:
                        continue
        except OSError:
            pass
        return top

    def _read_lease(self) -> dict | None:
        try:
            with open(os.path.join(self.dir, LEASE_FILE), encoding="utf-8") as f:
                d = json.load(f)
            return d if isinstance(d, dict) else None
        except (OSError, ValueError):
            return None  # missing or torn — stale by definition

    def observed(self) -> tuple[int, str]:
        """(term, holder) of the newest leadership anyone advertised —
        what a fenced supervisor names in its abort message.  The holder
        is ``"?"`` while a winner has claimed but not yet heartbeat."""
        top = self._scan_top_claim()
        lease = self._read_lease()
        if lease and int(lease.get("term", 0)) >= top:
            return int(lease.get("term", 0)), str(lease.get("holder", "?"))
        return top, "?"

    def _staleness(self, top: int) -> float:
        """Seconds since the newest sign of a live holder (claim-file
        mtime or heartbeat stamp) — inf when there has never been one."""
        newest = -float("inf")
        lease = self._read_lease()
        if lease and int(lease.get("term", 0)) >= top:
            try:
                newest = max(newest, float(lease.get("stamp", 0.0)))
            except (TypeError, ValueError):
                pass
        if top > 0:
            try:
                newest = max(
                    newest,
                    os.path.getmtime(os.path.join(self.dir, _claim_name(top))),
                )
            except OSError:
                pass
        return time.time() - newest  # inf when newest stayed -inf

    # -- acquisition ------------------------------------------------------
    def acquire(self, *, stop: threading.Event | None = None,
                timeout: float | None = None) -> int:
        """Block until this process wins the lease; returns the term.

        Waits for the incumbent (if any) to go stale past
        ``STEAL_FACTOR * ttl``, then claims the next term with
        ``O_CREAT | O_EXCL`` — losing the creation race just means
        someone else won that term, and the loop waits on THEIR
        freshness.  ``timeout`` bounds the wait with a typed
        :class:`StallError`; ``stop`` aborts it cooperatively.
        """
        # chaos site: the lease cannot be claimed at startup (readonly /
        # unreachable lease volume) — abort typed before spawning hosts
        faults.fire("lease.acquire")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            top = self._scan_top_claim()
            if top == 0 or self._staleness(top) > STEAL_FACTOR * self.ttl:
                try:
                    fd = os.open(
                        os.path.join(self.dir, _claim_name(top + 1)),
                        os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                        0o644,
                    )
                except FileExistsError:
                    continue  # lost the race for this term; re-observe
                except OSError as e:
                    raise WalQuarantine(
                        f"cannot claim lease term {top + 1} in "
                        f"{self.dir!r}: {e}"
                    ) from e
                try:
                    os.write(fd, self.holder.encode("utf-8", "replace"))
                finally:
                    os.close(fd)
                self.term = top + 1
                self._observed_fence = False
                self._last_renew = time.monotonic()
                self._write_stamp()
                return self.term
            if stop is not None and stop.is_set():
                raise StallError("lease acquisition cancelled")
            if deadline is not None and time.monotonic() > deadline:
                t, h = self.observed()
                raise StallError(
                    f"lease acquisition timed out after {timeout:.1f}s: "
                    f"term {t} held by {h!r} is still fresh"
                )
            time.sleep(min(0.05, self.ttl / 8 or 0.05))

    def _write_stamp(self) -> None:
        _atomic_write_json(
            os.path.join(self.dir, LEASE_FILE),
            {"term": self.term, "holder": self.holder, "stamp": time.time()},
        )

    # -- renewal / fencing ------------------------------------------------
    def renew(self, *, stop: threading.Event | None = None) -> None:
        """One heartbeat: re-stamp the lease, or discover we are fenced.

        Raises ``InjectedFault`` when the ``lease.renew`` chaos seam is
        armed (the heartbeat thread then stops renewing FOREVER — the
        partition persists, and self-fencing by age takes over)."""
        # chaos site: the holder's renewal fails and stays failed
        # (partition / storage freeze) — it must self-fence within TTL
        faults.fire("lease.renew", stop=stop)
        if self._scan_top_claim() > self.term:
            if not self._observed_fence:
                self._observed_fence = True
                cb = self._on_fenced
                if cb is not None:
                    cb()
            return
        try:
            self._write_stamp()
        except OSError:
            return  # renewal failed; age keeps growing toward self-fence
        self._last_renew = time.monotonic()
        self.renews += 1

    @property
    def fenced(self) -> bool:
        """True the moment this holder may no longer publish: it saw a
        higher term claimed, or its own renewals have been failing
        longer than the TTL (a successor could win any moment)."""
        return self._observed_fence or self.age() > self.ttl

    def age(self) -> float:
        """Seconds since the last successful renewal."""
        return time.monotonic() - self._last_renew

    def describe(self) -> dict:
        """One JSON-able snapshot of the leadership state — the lineage
        plane's ``term``/``path`` stamps and the doctor's postmortem
        join both read leadership from here rather than re-deriving it
        from the stamp file (one fencing law, one reader)."""
        return {
            "term": int(self.term),
            "holder": self.holder,
            "ttl_sec": float(self.ttl),
            "age_sec": round(self.age(), 3),
            "fenced": bool(self.fenced),
            "renews": int(self.renews),
        }

    # -- heartbeat thread -------------------------------------------------
    def start_heartbeat(self, on_fenced=None) -> None:
        """Renew every ``ttl/4`` from a daemon thread; ``on_fenced``
        fires (once, from that thread) when a higher term is observed."""
        from ..errors import InjectedFault

        self._on_fenced = on_fenced

        def _beat() -> None:
            while not self._stop.is_set():
                try:
                    self.renew(stop=self._stop)
                except InjectedFault:
                    return  # stop renewing forever: the partition persists
                if self._observed_fence:
                    return
                self._stop.wait(self.ttl / 4)

        self._thread = threading.Thread(
            target=_beat, daemon=True, name="ra-lease-hb"
        )
        self._thread.start()

    def release(self) -> None:
        """Planned handoff: stop heartbeating and clear the stamp so a
        successor need not wait out the staleness window.  A fenced
        holder leaves ``lease.json`` alone — it belongs to the winner."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # ``self.fenced``, not just the observed flag: an age-fenced
        # holder may already have a successor it never saw — unlinking
        # here would let a third party steal the winner's term early
        if not self.fenced and self.term > 0:
            try:
                os.unlink(os.path.join(self.dir, LEASE_FILE))
            except OSError:
                pass


class EpochSpool(WriteAheadLog):
    """Durable per-host spool of RAEP1 window-epoch frames.

    Exactly the WAL discipline with epoch-sized records: segments are
    ``seg-<start_seq>.wal`` files under ``RASPOOL1`` magic, each record
    one complete RAEP1 frame (which carries its own CRCs too — a
    replayed payload still goes through ``unpack_epoch_payload``'s
    typed refusal before it can touch a merge).  ``replay(from_seq)``
    yields ``(seq, payload_bytes)``.
    """

    _MAGICS = (SPOOL_MAGIC,)
    _WRITE_MAGIC = SPOOL_MAGIC
    _MAX_RECORD = MAX_EPOCH_BYTES

    def __init__(self, spool_dir: str, *, budget_bytes: int = 64 << 20):
        super().__init__(
            spool_dir,
            # epoch records are large; size segments so small test
            # budgets stay legal (budget >= 2 * segment) and eviction
            # granularity stays one-or-few epochs
            segment_bytes=min(4 << 20, max(4096, budget_bytes // 2)),
            budget_bytes=budget_bytes,
        )

    def append_epoch(self, payload: bytes) -> int:
        """Durably spool one packed epoch BEFORE it ships; returns seq.

        Raises ``InjectedFault`` when the ``dist.epoch.spool`` seam is
        armed (full/readonly volume analog) — the host must degrade the
        spool subsystem and keep ingesting, never die."""
        faults.fire("dist.epoch.spool")
        return self.append_bytes(payload)

    @classmethod
    def _decode_record(cls, payload: bytes, magic: bytes) -> tuple:
        return (payload,)
