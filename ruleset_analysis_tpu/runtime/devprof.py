"""Device-step attribution plane: named-scope profiling + capture windows.

DESIGN §8's scatter-wall numbers were derived BY HAND from a one-off
``jax.profiler`` capture keyed on opaque XLA fusion names (``fusion.5``,
``fusion.7``) that silently remap on any compiler or code change.  This
module makes device attribution repeatable, semantic, and diffable —
the "attribute before you optimize" discipline the scatter-wall attack
(ROADMAP item 2) and the two stage-vs-step inversions (VERDICT Weak
#2/#3) are blocked on.  Three legs (DESIGN §14):

- **Semantic naming.**  Every register-update stage in ``ops/`` and the
  dispatch seams in ``parallel/step.py`` trace under ``jax.named_scope``
  labels (the ``ra.*`` taxonomy: :data:`STAGES`).  Scopes ride HLO op
  *metadata* (``op_name``) through XLA's optimizer, so fusions — even
  renumbered ones — carry the stages they fused.  Trace-time only:
  zero runtime cost, bit-identical outputs.

- **In-process capture windows.**  :class:`DevprofCapture` arms
  ``jax.profiler`` programmatically for a bounded N-dispatch window
  after a warmup (``run/serve --devprof-out DIR [--devprof-steps N]``),
  then parses the trace IN-PROCESS: each profiled event maps through
  the program's *optimized* HLO (re-derived via ``jit.lower(...).
  compile()`` with sharding-preserving abstract args — deterministic
  compilation reproduces the executed module, names included) to the
  outermost ``ra.*`` scope of its instruction's metadata.  The summary
  adds static ``compiled.cost_analysis()`` FLOPs/bytes per program and
  a per-stage instruction/output-byte footprint from the HLO itself,
  lands in ``OUT/devprof.json``, the report's ``totals.devprof`` block,
  the metrics JSONL, and the serve ``/metrics`` gauges.  The arming
  discipline is ``obs.py``'s: disarmed cost is one module-global
  None-check per dispatch.

- **Shared classifier.**  :func:`scope_of` / :func:`classify_event_name`
  are the ONE definition of "which stage does this op belong to" —
  ``tools/trace_attrib.py`` (offline captures) and this module
  (in-process) import the same functions, so offline and in-process
  attribution can never disagree.  ``tools/trace_diff.py`` consumes two
  ``devprof.json`` captures and emits the per-stage delta table with
  fusion-boundary change detection.

Failure model: the ``devprof.capture`` fault site fires at profiler
start AND stop — an injected (or real) profiler failure is a typed
abort or a clean no-trace run (the error is recorded in the summary),
never a hang or a corrupted report.  Single-controller only: the
capture window and trace parse run in one process, so the CLI refuses
``--devprof-out`` under ``--distributed`` multi-process.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import threading
import time

from . import faults, obs

# The stage taxonomy (DESIGN §14) is single-sourced in
# ruleset_analysis_tpu/stages.py — this module, tools/trace_attrib.py,
# and the static lint plane (verify/) all import the SAME tuple, so the
# three consumers can never drift.  Re-exported here because this module
# historically owned it and callers import devprof.STAGES.
from ..stages import SCOPE_RE as _SCOPE_RE  # noqa: F401
from ..stages import STAGES, scope_of  # noqa: F401

#: HLO dtype -> bytes per element (static footprint accounting).
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

_SHAPE_RE = re.compile(r"^([a-z]\w*)\[([0-9,]*)\]")


def classify_event_name(name: str, args: dict | None = None) -> str | None:
    """Stage of one raw trace event, from its name or its args.

    The offline half of the shared classifier (tools/trace_attrib.py):
    TPU device tracks carry the full scope path in the event name or in
    metadata-ish args (``long_name``/``tf_op``/``name``); CPU thunk
    events don't — those need the HLO op index an in-process capture
    builds (:func:`parse_hlo_module`).  Returns None when no ``ra.*``
    token is present anywhere (callers fall back to the raw name).
    """
    s = scope_of(name)
    if s is not None:
        return s
    for k in ("long_name", "tf_op", "name", "op_name", "hlo_op"):
        v = (args or {}).get(k)
        if isinstance(v, str):
            s = scope_of(v)
            if s is not None:
                return s
    return None


def _shape_bytes(shape_text: str) -> int:
    """Byte size of one HLO array shape literal (``u32[34,16]{1,0}``).

    Tuple shapes (while/call results) and unknown dtypes report 0 —
    wrappers' footprints are their bodies', already counted.
    """
    m = _SHAPE_RE.match(shape_text.strip())
    if not m:
        return 0
    nbytes = _DTYPE_BYTES.get(m.group(1))
    if nbytes is None:
        return 0
    n = 1
    for d in filter(None, m.group(2).split(",")):
        n *= int(d)
    return n * nbytes


_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->")
_INSTR_RE = re.compile(
    r"^\s+(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"
    r"((?:\([^)]*\))|(?:[a-z]\w*\[[0-9,]*\]\S*))\s+([\w\-]+)\("
)
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")


def parse_hlo_module(text: str) -> dict:
    """Index one optimized HLO module for attribution.

    Returns::

        {
          "entry": {instr_name: {"scope", "op", "bytes"}},   # entry computation
          "nested": {instr_name, ...},                        # body instr names
          "fusions": [{"name", "op", "stages": [...]}, ...],  # per fusion instr
        }

    ``entry`` drives event classification: profiled events are counted
    for ENTRY-computation instructions only (their durations contain
    any nested body work, so counting bodies too would double-count).
    ``fusions`` records, for every fusion instruction in ANY
    computation, the set of distinct stages of the instructions inside
    its fused computation — the fusion-boundary signature trace_diff's
    change detection compares.
    """
    entry: dict[str, dict] = {}
    nested: set[str] = set()
    comp_instrs: dict[str, list[tuple[str, str]]] = {}  # comp -> [(instr scope, op)]
    fusion_instrs: list[tuple[str, str, str]] = []  # (name, op_name, called comp)
    cur = None
    in_entry = False
    for line in text.splitlines():
        if not line.startswith((" ", "\t")):
            m = _COMP_RE.match(line)
            if m:
                cur = m.group(2)
                in_entry = bool(m.group(1))
            continue
        m = _INSTR_RE.match(line)
        if m is None or cur is None:
            continue
        name, shape, op = m.group(1), m.group(2), m.group(3)
        om = _OPNAME_RE.search(line)
        op_name = om.group(1) if om else ""
        comp_instrs.setdefault(cur, []).append((op_name, op))
        if op == "fusion":
            cm = _CALLS_RE.search(line)
            if cm:
                fusion_instrs.append((name, op_name, cm.group(1)))
        if in_entry:
            entry[name] = {
                "scope": scope_of(op_name),
                "op": op,
                "bytes": _shape_bytes(shape),
            }
        else:
            nested.add(name)
    fusions = []
    for name, op_name, called in fusion_instrs:
        stages = sorted(
            {
                s
                for inner_op_name, _op in comp_instrs.get(called, [])
                for s in [scope_of(inner_op_name)]
                if s is not None
            }
        )
        outer = scope_of(op_name)
        if outer is not None and outer not in stages:
            stages = sorted(set(stages) | {outer})
        fusions.append({"name": name, "stages": stages})
    return {"entry": entry, "nested": nested, "fusions": fusions}


def _sds_of(x):
    """Sharding-preserving ShapeDtypeStruct of one dispatch argument.

    Single-device (uncommitted) shardings normalize to None — mixing a
    lone SingleDeviceSharding (the salt scalar) with the mesh-committed
    registers would make ``lower`` reject the signature the real
    dispatch accepted.
    """
    import jax

    s = getattr(x, "sharding", None)
    try:
        if s is not None and len(s.device_set) <= 1:
            s = None
    except Exception:
        s = None
    if s is not None:
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)
    import numpy as _np

    arr = _np.asarray(x) if not hasattr(x, "dtype") else x
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


#: (jit id, abstract-args repr) -> {"text", "cost"}.  A capture's
#: attribution re-derives the dispatched program's optimized HLO via
#: lower().compile(); for one program that's one XLA compile per
#: PROCESS, not per capture — a serve daemon capturing every few hours
#: (or a test suite capturing repeatedly) pays it once.  Keyed on the
#: jit object's identity (kept alive by the entry) + the abstract args,
#: bounded like step.py's specialized-jit cache.
_PROGRAM_CACHE: dict = {}
_PROGRAM_CACHE_MAX = 8


def _compiled_info(fn, args_sds) -> dict:
    key = (id(fn), str(jax_tree_repr(args_sds)))
    hit = _PROGRAM_CACHE.get(key)
    if hit is not None:
        return hit
    compiled = fn.lower(*args_sds).compile()
    info = {
        "text": compiled.as_text(),
        "cost": _norm_cost(compiled.cost_analysis()),
        "_fn": fn,  # keeps the id() key valid for the entry's lifetime
    }
    if len(_PROGRAM_CACHE) >= _PROGRAM_CACHE_MAX:
        _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
    _PROGRAM_CACHE[key] = info
    return info


def jax_tree_repr(tree) -> str:
    import jax

    # shardings participate: same shapes committed differently compile
    # to different modules, and the cache must never alias them
    return str(
        jax.tree_util.tree_map(
            lambda s: (s.shape, str(s.dtype), str(getattr(s, "sharding", None))),
            tree,
        )
    )


def _norm_cost(ca) -> dict:
    """``compiled.cost_analysis()`` -> {flops, bytes_accessed} (or {})."""
    try:
        d = ca[0] if isinstance(ca, (list, tuple)) else ca
        if not isinstance(d, dict):
            return {}
        out = {}
        if "flops" in d:
            out["flops"] = float(d["flops"])
        if "bytes accessed" in d:
            out["bytes_accessed"] = float(d["bytes accessed"])
        return out
    except Exception:
        return {}


def device_memory_gauges() -> dict:
    """Live device memory stats; graceful nulls where unsupported.

    ``jax.local_devices()[0].memory_stats()`` reports HBM occupancy on
    TPU/GPU; XLA:CPU returns nothing — the gauges then carry explicit
    ``None`` (JSON ``null``) so a dashboard shows "unsupported", never a
    fake zero.  The scatter-wall work (ROADMAP item 2) reads
    register-footprint headroom from exactly these gauges.
    """
    stats = None
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        stats = None
    keys = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
    if not stats:
        return {f"device_mem_{k}": None for k in keys}
    return {f"device_mem_{k}": stats.get(k) for k in keys}


class DevprofCapture:
    """One bounded in-process profiler window over the step dispatches.

    Dispatches 1..warmup run unprofiled (compile + cache warm); the
    profiler arms before dispatch warmup+1 and disarms after dispatch
    warmup+steps completes (output synced first — async backends must
    not close the window with work in flight).  Everything after is a
    plain pass-through, so a long run pays the capture cost once and
    the sustained rate barely moves (bench_suite ``steptrace`` pins the
    armed/disarmed ratio >= 0.98).
    """

    def __init__(self, out_dir: str, steps: int = 16, warmup: int = 3,
                 label: str = ""):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = os.path.abspath(out_dir)
        self.trace_dir = os.path.join(self.out_dir, "jax-trace")
        self.steps = int(steps)
        self.warmup = int(warmup)
        self.label = label
        self._lock = threading.Lock()
        self._count = 0
        self._profiling = False
        self._done = False
        self._pending_parse = False
        self._error: str | None = None
        self._summary: dict | None = None
        #: wall time the profiler was live (the bounded capture pause).
        #: Profiling a step is NOT free — on XLA:CPU every scatter-loop
        #: iteration emits a thunk event, so a profiled step can run
        #: 10-50x slower than a plain one.  The pause is priced apart
        #: from the run's sustained rate the same way compile is
        #: (bench_suite steptrace; DESIGN §14).
        self._window_wall: float | None = None
        self._t_window0: float | None = None
        #: label -> {"fn", "args_sds", "dispatches"} (programs seen in-window)
        self._programs: dict[str, dict] = {}

    # -- dispatch seam ---------------------------------------------------

    def dispatch(self, label: str, fn, args):
        """Run one device dispatch, advancing the capture window."""
        if self._done:
            return fn(*args)
        start = stop = False
        with self._lock:
            if self._done:
                return fn(*args)
            self._count += 1
            if not self._profiling and self._count == self.warmup + 1:
                start = True
            if self._profiling or start:
                prog = self._programs.get(label)
                if prog is None:
                    import jax

                    prog = self._programs[label] = {
                        "fn": fn,
                        "args_sds": jax.tree_util.tree_map(_sds_of, args),
                        "dispatches": 0,
                    }
                prog["dispatches"] += 1
                if self._count >= self.warmup + self.steps:
                    stop = True
        if start:
            import jax

            # quiesce before opening the window: async backends (and
            # XLA:CPU's thread-pool executor) may still be running the
            # warmup dispatches, whose tail would otherwise execute —
            # and be taxed — inside the profiled window.  The state
            # argument IS the previous dispatch's output, so blocking
            # on the args drains everything in flight.
            jax.block_until_ready(args)
            self._start()
            if self._done:  # start failed: clean no-trace run
                return fn(*args)
        out = fn(*args)
        if stop and self._profiling:
            import jax

            jax.block_until_ready(out)
            self._close_window()
        return out

    # -- window control --------------------------------------------------

    def _start(self) -> None:
        # the fault site fires OUTSIDE the try: an injected failure is a
        # typed abort (InjectedFault), while a REAL profiler failure
        # degrades to a clean no-trace run with the error recorded
        faults.fire("devprof.capture")
        import jax

        # the pause clock starts BEFORE start_trace: profiler backend
        # init is part of the capture's cost, not the run's
        t0 = time.perf_counter()
        try:
            jax.profiler.start_trace(self.trace_dir)
        except Exception as e:
            self._error = f"profiler start failed: {e}"
            self._done = True
            return
        self._t_window0 = t0
        self._profiling = True

    def _close_window(self) -> None:
        """Stop the profiler at the window boundary (cheap, mid-run).

        The expensive half — re-deriving the optimized HLO and parsing
        the trace — is DEFERRED to :meth:`finalize` / :meth:`poll`, so
        it can never pollute the run's measured elapsed/sustained rate
        (the drivers capture ``elapsed`` before finalizing).
        """
        self._done = True
        try:
            # typed-abort seam: an injected stop failure propagates, and
            # abort() below still stops the live profiler on the way out
            faults.fire("devprof.capture")
        except BaseException:
            self.abort()
            raise
        self._profiling = False
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception as e:
            self._error = f"profiler stop failed: {e}"
            return
        if self._t_window0 is not None:
            self._window_wall = time.perf_counter() - self._t_window0
        self._pending_parse = True

    def _ensure_parsed(self) -> None:
        if not self._pending_parse:
            return
        self._pending_parse = False
        try:
            self._summary = self._parse()
        except Exception as e:  # attribution must never kill the run
            self._error = f"trace parse failed: {e}"
            return
        self._emit(self._summary)

    def poll(self) -> None:
        """Parse a CLOSED window if one is waiting (serve's rotation seam
        — never closes an open window early)."""
        self._ensure_parsed()

    def abort(self) -> None:
        """Stop a dangling profiler without parsing (typed-abort path)."""
        if self._profiling:
            self._profiling = False
            self._done = True
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass

    def finalize(self) -> dict:
        """Close the window (stream may end early) and return the summary.

        Idempotent; always returns a dict — a window that never opened
        (stream shorter than the warmup) or failed reports itself
        explicitly instead of pretending a capture happened.
        """
        if self._profiling:
            self._close_window()
        self._done = True
        self._ensure_parsed()
        if self._summary is not None:
            return self._summary
        out = {
            "steps_profiled": 0,
            "requested_steps": self.steps,
            "warmup": self.warmup,
        }
        if self.label:
            out["label"] = self.label
        if self._error is not None:
            out["error"] = self._error
        else:
            out["note"] = (
                "stream ended before the capture window opened "
                f"(saw {self._count} dispatches, warmup {self.warmup})"
            )
        return out

    # -- attribution -----------------------------------------------------

    def _newest_trace(self) -> str | None:
        pats = ("*.trace.json.gz", "*.trace.json")
        hits: list[str] = []
        for p in pats:
            hits += glob.glob(
                os.path.join(self.trace_dir, "plugins", "profile", "*", p)
            )
        return max(hits, key=os.path.getmtime) if hits else None

    def _program_info(self) -> tuple[dict, dict]:
        """(merged entry op index, per-program static info).

        Re-lowers each in-window program with its recorded abstract
        args (shardings preserved) and compiles it — XLA compilation is
        deterministic for an identical module, so instruction names
        match the executed program's trace events.  With the persistent
        compilation cache armed (runtime/compcache.py) this is a cache
        read, not a second compile.
        """
        index: dict[str, dict] = {}
        programs: dict[str, dict] = {}
        for label, prog in sorted(self._programs.items()):
            info = _compiled_info(prog["fn"], prog["args_sds"])
            cost = info["cost"]
            mod = parse_hlo_module(info["text"])
            static: dict[str, dict] = {}
            for name, instr in mod["entry"].items():
                stage = instr["scope"] or "unattributed"
                st = static.setdefault(
                    stage, {"instructions": 0, "out_bytes": 0}
                )
                st["instructions"] += 1
                st["out_bytes"] += instr["bytes"]
                prev = index.get(name)
                if prev is not None and prev.get("scope") != instr["scope"]:
                    # same instruction name, different stage in another
                    # program: ambiguous — classify as unattributed
                    # rather than guess (distinct programs rarely share
                    # hot-op names; conflicts are counted)
                    index[name] = {"scope": None, "op": instr["op"], "ambiguous": True}
                else:
                    index[name] = {"scope": instr["scope"], "op": instr["op"]}
            programs[label] = {
                "dispatches": prog["dispatches"],
                "hlo_instructions": len(mod["entry"]),
                "stages_static": dict(sorted(static.items())),
                "fusions": mod["fusions"],
                **cost,
            }
        return index, programs

    def _parse(self) -> dict:
        trace_path = self._newest_trace()
        index, programs = self._program_info()
        stages_us: dict[str, float] = {}
        stage_events: dict[str, int] = {}
        unattributed_us = 0.0
        n_events = 0
        if trace_path is not None:
            opener = gzip.open if trace_path.endswith(".gz") else open
            with opener(trace_path, "rt", encoding="utf-8") as f:
                data = json.load(f)
            for e in data.get("traceEvents", []):
                if e.get("ph") != "X" or "dur" not in e:
                    continue
                info = index.get(e.get("name", ""))
                if info is None:
                    continue  # nested-body or host-runtime event
                n_events += 1
                scope = info.get("scope")
                if scope is None:
                    unattributed_us += e["dur"]
                else:
                    stages_us[scope] = stages_us.get(scope, 0.0) + e["dur"]
                    stage_events[scope] = stage_events.get(scope, 0) + 1
        total_us = sum(stages_us.values()) + unattributed_us
        stages = {
            s: {
                "device_us": round(us, 1),
                "pct": round(100.0 * us / total_us, 2) if total_us else 0.0,
                "events": stage_events.get(s, 0),
            }
            for s, us in sorted(stages_us.items(), key=lambda kv: -kv[1])
        }
        cross = [
            {"program": label, "name": f["name"], "stages": f["stages"]}
            for label, prog in programs.items()
            for f in prog["fusions"]
            if len(f["stages"]) > 1
        ]
        steps_profiled = sum(p["dispatches"] for p in self._programs.values())
        import jax

        out = {
            "requested_steps": self.steps,
            "warmup": self.warmup,
            "steps_profiled": steps_profiled,
            #: the bounded pause the live profiler cost this run — price
            #: it apart from the sustained rate, like compile_sec
            "window_wall_sec": (
                round(self._window_wall, 3)
                if self._window_wall is not None
                else None
            ),
            "backend": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            "device_us_total": round(total_us, 1),
            "attributed_frac": (
                round(1.0 - unattributed_us / total_us, 4) if total_us else 0.0
            ),
            "unattributed": {
                "device_us": round(unattributed_us, 1),
                "pct": (
                    round(100.0 * unattributed_us / total_us, 2)
                    if total_us
                    else 0.0
                ),
            },
            "stages": stages,
            "programs": programs,
            "cross_stage_fusions": cross,
            "trace_path": trace_path,
            "memory": device_memory_gauges(),
        }
        if self.label:
            out["label"] = self.label
        if self._error:
            out["error"] = self._error
        return out

    def _emit(self, summary: dict) -> None:
        path = os.path.join(self.out_dir, "devprof.json")
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=2)
        os.replace(tmp, path)
        self.json_path = path
        # the obs planes carry the capture (trace instant for
        # trace_summary's devprof block; metrics event for the JSONL)
        brief = self.gauges()
        obs.instant("devprof.summary", args=brief)
        obs.metric_event("devprof", **brief)

    def gauges(self) -> dict:
        """Flat numeric gauges for /metrics (JSON + prom) and the JSONL."""
        s = self._summary
        if s is None:
            return {"devprof_steps_profiled": 0}
        g = {
            "devprof_steps_profiled": s["steps_profiled"],
            "devprof_attributed_frac": s["attributed_frac"],
            "devprof_device_us_total": s["device_us_total"],
        }
        top = next(iter(s["stages"]), None)
        if top is not None:
            g["devprof_top_stage"] = top
            g["devprof_top_stage_pct"] = s["stages"][top]["pct"]
        for name, st in s["stages"].items():
            g[f"devprof_pct_{name.replace('.', '_')}"] = st["pct"]
        return g


# ---------------------------------------------------------------------------
# Module arming state — the faults.py discipline: ``_capture is None`` is
# the production fast path (one None-check per device dispatch).
# ---------------------------------------------------------------------------

_capture: DevprofCapture | None = None


def arm(out_dir: str, steps: int = 16, warmup: int = 3, label: str = "") -> DevprofCapture:
    """Arm a capture window process-wide (``--devprof-out``).

    Single-controller only: the window brackets THIS process's
    dispatches and the parse reads this process's trace.  Also registers
    the devprof + device-memory gauges with the metrics plane (no-ops
    when ``--metrics-out`` is not armed).
    """
    global _capture
    from ..config import DevprofConfig
    from ..errors import AnalysisError

    try:
        # ONE definition of the limits: the config dataclass validates
        # for the CLI and for programmatic callers alike
        DevprofConfig(out_dir=out_dir, steps=steps, warmup=warmup)
    except ValueError as e:
        raise AnalysisError(str(e)) from e
    cap = DevprofCapture(out_dir, steps=steps, warmup=warmup, label=label)
    _capture = cap
    obs.register_sampler("devprof", cap.gauges)
    obs.register_sampler("device_mem", device_memory_gauges)
    return cap


def active_capture() -> DevprofCapture | None:
    """The armed capture (the hot-path accessor: one None-check)."""
    return _capture


def gauges() -> dict:
    """Armed capture's flat gauges, or {} — serve /metrics folds these."""
    cap = _capture
    return cap.gauges() if cap is not None else {}


def finalize_if_armed() -> dict | None:
    """Driver seam: close the window and return the ``totals.devprof``
    block (None when disarmed).  The capture stays armed so gauges keep
    answering until :func:`shutdown`."""
    cap = _capture
    if cap is None:
        return None
    return cap.finalize()


def shutdown() -> None:
    """Disarm; stop any dangling profiler (abort path) without parsing."""
    global _capture
    cap = _capture
    _capture = None
    if cap is not None:
        cap.abort()
        obs.unregister_sampler("devprof")
        obs.unregister_sampler("device_mem")
