"""Durable serve ingest WAL: segmented, CRC'd on-disk line spool.

The serve checkpoint plane protects *rotated* windows; every line of the
in-progress window lived only in memory, so a SIGKILL mid-window lost it
— the interrupted window could never publish.  This module closes that
gap (DESIGN §19): every line the serve loop consumes appends here BEFORE
window accounting, and ``serve --resume`` replays the tail past the last
checkpoint so the interrupted window publishes **bit-identical over its
delivered lines**.

The distributed service (``serve --distributed``, DESIGN §22) keeps one
WAL **per ingest host** under ``serve_dir/host-<rank>/wal`` — spools are
strictly host-local (a host appends only its own listeners' lines), so
a whole-host SIGKILL replays exactly that host's tail on rejoin, and
rank 0 tracks two cursors per host: the seq covered by *received*
epochs (the rejoin replay point) and the seq covered by *published*
windows (what the merged-ring checkpoint records — a supervisor death
must re-merge pending-but-unpublished epochs from the spool).

Design:

- **Segments.**  ``seg-<start_seq>.wal`` files; each holds a 16-byte
  header (magic + little-endian u64 first-record seq) followed by
  length-prefixed records (``u32 len | u32 crc32(payload) | payload``).
  Records are implicitly numbered ``start_seq + index`` — seq arithmetic
  is what makes every loss *exactly countable*: the records missing
  between a checkpoint's seq and the first available record is their
  difference, no side counters to trust.

- **Durability.**  Appends are single ``os.write`` calls on an O_APPEND
  fd — SIGKILL-safe by construction (the bytes are in the kernel).
  ``sync()`` fsyncs the open segment for power-loss durability; serve
  calls it at every ring checkpoint.

- **Bounded disk.**  When live segments exceed ``budget_bytes``, the
  OLDEST segment is evicted and its record count charged to
  ``evicted_records`` — an explicit, exact drop class.  A later resume
  whose checkpoint seq predates the surviving head observes the gap via
  seq arithmetic and reports it as ``replay_lost`` (never a silent gap).

- **Corruption.**  A record whose CRC fails — or broken framing in a
  non-final segment — quarantines the segment from that record on: the
  file is renamed ``*.quarantined``, the remaining records are counted
  exactly when a successor segment pins the end seq (unknown only for a
  corrupt FINAL segment's tail), and replay continues with the next
  segment.  A short record at the very end of the FINAL segment is not
  corruption: it is the torn tail of the append the kill interrupted,
  and replay ends cleanly there.

Used by ``runtime/serve.py`` (``serve --wal``); unit-pinned in
tests/test_wal.py without any device work.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib

from ..errors import AnalysisError, WalQuarantine

MAGIC = b"RAWAL1\x00\x00"  # 8 bytes — v1: payload IS the line
#: v2 (ISSUE 16): payload = u8 tenant-key length | tenant utf-8 | line
#: utf-8.  The version is per SEGMENT (header magic), so a pre-tenancy
#: spool and the segments a tenant-aware process appends after it replay
#: as one chain; v1 records decode with the default tenant key.
MAGIC2 = b"RAWAL2\x00\x00"
#: tenant key of every record written before the tenancy plane existed,
#: and of single-tenant serve processes after it (runtime/tenancy.py
#: re-exports this as the registry's default tenant name)
DEFAULT_TENANT = "default"
_HDR = struct.Struct("<8sQ")  # magic, start_seq
_REC = struct.Struct("<II")  # payload len, payload crc32
HEADER_BYTES = _HDR.size
#: framing sanity bound: no single syslog line is this big (the listener
#: tier already drops >1 MiB lines); a larger length word means the
#: segment's framing is broken, i.e. corruption
MAX_RECORD_BYTES = 4 << 20


def _seg_name(start_seq: int) -> str:
    return f"seg-{start_seq:020d}.wal"


class _BadRecord(Exception):
    """A CRC-valid record that fails its format's payload framing —
    a writer bug, not disk damage, but still a typed quarantine."""


class _Segment:
    __slots__ = ("path", "start", "count", "bytes")

    def __init__(self, path: str, start: int, count: int, nbytes: int):
        self.path = path
        self.start = start
        self.count = count  # records known to be in the file
        self.bytes = nbytes

    @property
    def end(self) -> int:
        return self.start + self.count


class WriteAheadLog:
    """One serve process's ingest WAL (single-writer, scan-on-open).

    The segment/eviction/quarantine machinery is format-parametric so
    the distributed-serve epoch spool (runtime/lease.py ``EpochSpool``)
    can reuse the whole discipline verbatim: subclasses override the
    three class attributes below plus :meth:`_decode_record` and get
    O_APPEND durability, seq-gap loss accounting, and typed quarantine
    for free.
    """

    #: segment-header magics this format accepts on replay
    _MAGICS: tuple[bytes, ...] = (MAGIC, MAGIC2)
    #: segment-header magic new segments are written with
    _WRITE_MAGIC: bytes = MAGIC2
    #: framing sanity bound for one record's payload
    _MAX_RECORD: int = MAX_RECORD_BYTES

    def __init__(
        self,
        wal_dir: str,
        *,
        segment_bytes: int = 1 << 20,
        budget_bytes: int = 64 << 20,
    ):
        if segment_bytes < 4096:
            raise WalQuarantine(
                f"wal segment_bytes must be >= 4096, got {segment_bytes}"
            )
        if budget_bytes < 2 * segment_bytes:
            raise WalQuarantine(
                "wal budget_bytes must be >= 2 * segment_bytes"
            )
        self.dir = os.path.abspath(wal_dir)
        self.segment_bytes = segment_bytes
        self.budget_bytes = budget_bytes
        try:
            os.makedirs(self.dir, exist_ok=True)
        except OSError as e:
            raise WalQuarantine(
                f"cannot create WAL directory {wal_dir!r}: {e}"
            ) from e
        self._lock = threading.Lock()
        self._fd: int | None = None  # open (rolling) segment fd
        self.appended = 0  # records appended by THIS process
        self.evicted_segments = 0
        self.evicted_records = 0
        #: set by the last replay(): records known lost to eviction /
        #: quarantine before or during it (exact where seq math allows)
        self.replay_lost = 0
        #: True when a corrupt FINAL segment made the tail loss uncountable
        self.replay_lost_unknown = False
        self.quarantined: list[str] = []
        self._segments: list[_Segment] = self._scan()
        self.next_seq = self._segments[-1].end if self._segments else 0

    # -- scan -------------------------------------------------------------
    def _scan(self) -> list[_Segment]:
        """Index existing segments; only the LAST needs a record walk
        (every earlier segment's count is pinned by its successor's
        start seq)."""
        segs: list[_Segment] = []
        try:
            names = sorted(
                n for n in os.listdir(self.dir)
                if n.startswith("seg-") and n.endswith(".wal")
            )
        except OSError as e:
            raise WalQuarantine(f"cannot scan WAL dir {self.dir!r}: {e}") from e
        starts = []
        for n in names:
            try:
                starts.append((int(n[4:-4]), n))
            except ValueError:
                continue  # foreign file; ignored
        starts.sort()
        for i, (start, n) in enumerate(starts):
            path = os.path.join(self.dir, n)
            nbytes = os.path.getsize(path)
            if i + 1 < len(starts):
                count = starts[i + 1][0] - start
            else:
                count = self._count_records(path)
            segs.append(_Segment(path, start, count, nbytes))
        return segs

    @classmethod
    def _count_records(cls, path: str) -> int:
        """Record count of the final segment (torn tail tolerated)."""
        n = 0
        try:
            with open(path, "rb") as f:
                hdr = f.read(HEADER_BYTES)
                if len(hdr) < HEADER_BYTES or hdr[:8] not in cls._MAGICS:
                    return 0  # quarantined at replay; count unknown
                while True:
                    rec = f.read(_REC.size)
                    if len(rec) < _REC.size:
                        return n
                    ln, _crc = _REC.unpack(rec)
                    if ln > cls._MAX_RECORD:
                        return n  # broken framing; replay quarantines
                    payload = f.read(ln)
                    if len(payload) < ln:
                        return n  # torn tail
                    n += 1
        except OSError:
            return n

    # -- append path ------------------------------------------------------
    def _open_segment(self) -> None:
        path = os.path.join(self.dir, _seg_name(self.next_seq))
        # a leftover zero-record segment (or an unreadable-header file)
        # may already hold this name; O_APPEND onto it would double the
        # header, so replace it — it contains no counted records
        if (
            self._segments
            and self._segments[-1].start == self.next_seq
            and self._segments[-1].count == 0
        ):
            self._segments.pop()
        try:
            os.unlink(path)
        except OSError:
            pass
        seg = _Segment(path, self.next_seq, 0, HEADER_BYTES)
        fd = os.open(seg.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.write(fd, _HDR.pack(self._WRITE_MAGIC, seg.start))
        self._fd = fd
        self._segments.append(seg)

    def append(self, line: str, tenant: str = DEFAULT_TENANT) -> int:
        """Durably spool one line; returns its seq (kernel-durable: one
        O_APPEND write — a SIGKILL after return cannot lose it).

        ``tenant`` is the routing key the record replays under (v2
        format); single-tenant serve never passes it and spools under
        :data:`DEFAULT_TENANT`.
        """
        tkey = tenant.encode("utf-8", errors="replace")
        if len(tkey) > 255:
            raise WalQuarantine(
                f"tenant key exceeds 255 bytes: {tenant[:64]!r}..."
            )
        payload = (
            bytes((len(tkey),)) + tkey + line.encode("utf-8", errors="replace")
        )
        return self.append_bytes(payload)

    def append_bytes(self, payload: bytes) -> int:
        """Durably spool one raw payload; returns its seq.

        The format-agnostic append path: the WAL's :meth:`append` frames
        (tenant, line) into it, the epoch spool appends RAEP1 frames
        directly."""
        rec = _REC.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload
        with self._lock:
            cur = self._segments[-1] if self._segments else None
            if (
                self._fd is None
                or cur is None
                or cur.bytes + len(rec) > self.segment_bytes
            ):
                self._roll()
                cur = self._segments[-1]
            seq = self.next_seq
            os.write(self._fd, rec)
            cur.count += 1
            cur.bytes += len(rec)
            self.next_seq = seq + 1
            self.appended += 1
            self._evict_over_budget()
        return seq

    def _roll(self) -> None:
        if self._fd is not None:
            try:
                os.fsync(self._fd)
            except OSError:
                pass
            os.close(self._fd)
            self._fd = None
        self._open_segment()

    def _evict_over_budget(self) -> None:
        total = sum(s.bytes for s in self._segments)
        while total > self.budget_bytes and len(self._segments) > 1:
            victim = self._segments.pop(0)
            total -= victim.bytes
            self.evicted_segments += 1
            self.evicted_records += victim.count
            try:
                os.unlink(victim.path)
            except OSError:
                pass
            from . import obs

            obs.instant("wal.evict", args={
                "segment": os.path.basename(victim.path),
                "records": victim.count,
            })

    def sync(self) -> None:
        """fsync the rolling segment (power-loss durability point)."""
        with self._lock:
            if self._fd is not None:
                try:
                    os.fsync(self._fd)
                except OSError:
                    pass

    def gc(self, upto_seq: int) -> int:
        """Drop segments wholly below ``upto_seq`` (checkpoint-covered).

        Returns the records released.  The rolling segment never drops.
        """
        freed = 0
        with self._lock:
            while len(self._segments) > 1 and self._segments[0].end <= upto_seq:
                seg = self._segments.pop(0)
                freed += seg.count
                try:
                    os.unlink(seg.path)
                except OSError:
                    pass
        return freed

    # -- replay path ------------------------------------------------------
    def replay(self, from_seq: int):
        """Yield ``(seq, line, tenant)`` for every record, seq >= from_seq.

        ``tenant`` is the record's routing key: v2 segments carry it in
        every record; records in v1 (pre-tenancy) segments replay under
        :data:`DEFAULT_TENANT` — the backward-compat contract the
        tenancy tests pin.

        Loss accounting lands on the instance afterwards: ``replay_lost``
        counts records known missing (evicted head gap + quarantined
        remainders pinned by a successor's start seq);
        ``replay_lost_unknown`` flags a corrupt FINAL segment whose tail
        count nothing pins.  CRC/framing corruption quarantines the
        segment (renamed ``*.quarantined``) and replay continues — never
        a crash, never a silent gap.
        """
        self.replay_lost = 0
        self.replay_lost_unknown = False
        segs = list(self._segments)
        if not segs:
            return
        if from_seq < segs[0].start:
            # evicted-head gap: exactly this many records are gone
            self.replay_lost += segs[0].start - from_seq
            from_seq = segs[0].start
        for i, seg in enumerate(segs):
            end = segs[i + 1].start if i + 1 < len(segs) else None
            if end is not None and end <= from_seq:
                continue
            yield from self._replay_segment(seg, from_seq, end)

    def _replay_segment(self, seg: _Segment, from_seq: int, end: int | None):
        try:
            f = open(seg.path, "rb")
        except OSError:
            self._quarantine(
                seg, max(seg.start, from_seq), end, "unreadable",
                countable_final=True,  # the open-time scan counted it
            )
            return
        with f:
            hdr = f.read(HEADER_BYTES)
            if len(hdr) < HEADER_BYTES or hdr[:8] not in self._MAGICS or (
                _HDR.unpack(hdr)[1] != seg.start
            ):
                self._quarantine(
                    seg, max(seg.start, from_seq), end, "bad segment header"
                )
                return
            magic = hdr[:8]
            seq = seg.start
            while True:
                rec = f.read(_REC.size)
                if len(rec) < _REC.size:
                    if end is not None and (rec or seq < end):
                        # mid-chain framing damage or a short segment
                        # whose successor pins more records than it holds
                        self._quarantine(
                            seg, max(seq, from_seq), end, "truncated record"
                        )
                    return  # clean end / torn tail of the final segment
                ln, crc = _REC.unpack(rec)
                if ln > self._MAX_RECORD:
                    self._quarantine(
                        seg, max(seq, from_seq), end, "absurd record length"
                    )
                    return
                payload = f.read(ln)
                if len(payload) < ln:
                    if end is not None:
                        self._quarantine(
                            seg, max(seq, from_seq), end, "truncated payload"
                        )
                    return  # torn tail of the final segment
                if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                    # CRC damage leaves the framing intact, so the scan's
                    # record count still pins the final segment's loss
                    self._quarantine(
                        seg, max(seq, from_seq), end, "record CRC mismatch",
                        countable_final=True,
                    )
                    return
                if seq >= from_seq:
                    try:
                        decoded = self._decode_record(payload, magic)
                    except _BadRecord as bad:
                        # CRC passed, so this is a writer bug, not disk
                        # damage — still a typed quarantine
                        self._quarantine(
                            seg, max(seq, from_seq), end, str(bad)
                        )
                        return
                    yield (seq, *decoded)
                seq += 1

    def read_record(self, seq: int) -> tuple | None:
        """Random-access read of ONE record by seq (decoded tuple), or
        ``None`` when no live segment covers it.

        The epoch store's range queries (DESIGN §25) hinge on this being
        cheap: the reader walks the covering segment's record *headers*
        (``f.seek`` past every other payload) and CRC-checks only the
        target, so a point read costs one header walk — not a replay of
        the chain.  Damage found on the walk quarantines the segment
        exactly like replay does (rename aside, loss pinned by seq math,
        successors untouched) and the read reports ``None``; the caller
        sees a typed gap, never bad bytes.
        """
        with self._lock:
            seg = next(
                (s for s in self._segments if s.start <= seq < s.end), None
            )
            succ = seg is not None and seg is not self._segments[-1]
        if seg is None:
            return None
        end = seg.end if succ else None
        try:
            f = open(seg.path, "rb")
        except OSError:
            self._quarantine(seg, seg.start, end, "unreadable",
                             countable_final=True)
            return None
        with f:
            hdr = f.read(HEADER_BYTES)
            if len(hdr) < HEADER_BYTES or hdr[:8] not in self._MAGICS or (
                _HDR.unpack(hdr)[1] != seg.start
            ):
                self._quarantine(seg, seg.start, end, "bad segment header")
                return None
            magic = hdr[:8]
            cur = seg.start
            while True:
                rec = f.read(_REC.size)
                if len(rec) < _REC.size:
                    return None  # torn tail before the target
                ln, crc = _REC.unpack(rec)
                if ln > self._MAX_RECORD:
                    self._quarantine(
                        seg, max(cur, seg.start), end, "absurd record length"
                    )
                    return None
                if cur < seq:
                    f.seek(ln, 1)  # skip payload unverified
                    cur += 1
                    continue
                payload = f.read(ln)
                if len(payload) < ln:
                    return None  # torn tail IS the target
                if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                    self._quarantine(
                        seg, cur, end, "record CRC mismatch",
                        countable_final=True,
                    )
                    return None
                try:
                    return self._decode_record(payload, magic)
                except _BadRecord as bad:
                    self._quarantine(seg, cur, end, str(bad))
                    return None

    @classmethod
    def _decode_record(cls, payload: bytes, magic: bytes) -> tuple:
        """Decode one CRC-valid payload into the tuple tail replay
        yields after the seq; raise :class:`_BadRecord` on framing a
        CRC cannot catch.  The WAL yields ``(line, tenant)``; the epoch
        spool overrides this to yield the raw payload."""
        if magic == MAGIC2:
            tlen = payload[0] if payload else 0
            if 1 + tlen > len(payload):
                raise _BadRecord("bad tenant framing")
            tenant = payload[1:1 + tlen].decode("utf-8", errors="replace")
            line = payload[1 + tlen:].decode("utf-8", errors="replace")
        else:
            tenant = DEFAULT_TENANT
            line = payload.decode("utf-8", errors="replace")
        return line, tenant

    def _note_lost(self, seg: _Segment, from_seq: int, end: int | None,
                   why: str, countable_final: bool) -> None:
        if end is not None:
            self.replay_lost += max(0, end - from_seq)
        elif countable_final and seg.count:
            # final segment with intact framing: the open-time scan's
            # record count pins the loss exactly
            self.replay_lost += max(0, seg.end - from_seq)
        else:
            self.replay_lost_unknown = True
        from . import obs

        obs.instant("wal.quarantine", args={
            "segment": os.path.basename(seg.path), "reason": why,
            "lost_from_seq": from_seq,
        })

    def _quarantine(self, seg: _Segment, from_seq: int, end: int | None,
                    why: str, countable_final: bool = False) -> None:
        """Typed quarantine: rename the damaged segment aside, count the
        loss where seq math pins it, keep replaying the successors."""
        self._note_lost(seg, from_seq, end, why, countable_final)
        qpath = seg.path + ".quarantined"
        try:
            os.replace(seg.path, qpath)
        except OSError:
            qpath = seg.path  # rename failed; leave in place, still counted
        self.quarantined.append(os.path.basename(qpath))
        with self._lock:
            if seg in self._segments:
                self._segments.remove(seg)
            if not self._segments:
                # the writer must not append into a quarantined chain
                self._fd = None

    # -- lifecycle --------------------------------------------------------
    def reset(self) -> None:
        """Delete every segment (a fresh, non-resume serve run starts a
        fresh log — stale spool from a previous analysis must not grow
        the dir forever)."""
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None
            for seg in self._segments:
                try:
                    os.unlink(seg.path)
                except OSError:
                    pass
            self._segments = []
            self.next_seq = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "next_seq": self.next_seq,
                "appended": self.appended,
                "segments": len(self._segments),
                "bytes": int(sum(s.bytes for s in self._segments)),
                "evicted_segments": self.evicted_segments,
                "evicted_records": self.evicted_records,
                "quarantined": list(self.quarantined),
            }

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.fsync(self._fd)
                except OSError:
                    pass
                os.close(self._fd)
                self._fd = None


class LineageLog:
    """Append-only ``lineage.jsonl``: the window provenance ledger.

    One JSON object per published window (DESIGN §24), written with the
    WAL's own durability idiom — a single ``os.write`` on an O_APPEND fd
    — so a record is either wholly present (newline-terminated) or not
    there at all.  A SIGKILL can tear at most the FINAL line, and a torn
    final line has no trailing newline, so :meth:`read` skips it the
    same way WAL replay treats a torn tail as a clean end, never as
    corruption.  Appending is a CORE publication step: it fires the
    ``lineage.append`` fault site and lets failures propagate typed —
    a window must never publish without its lineage record, so there is
    no publisher-style retry/degrade softening here.
    """

    NAME = "lineage.jsonl"

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fd = os.open(
            path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644
        )
        self.appended = 0

    def append(self, record: dict) -> None:
        from . import faults
        import json

        faults.fire("lineage.append")
        data = (
            json.dumps(record, sort_keys=True, separators=(",", ":")) + "\n"
        ).encode("utf-8")
        try:
            with self._lock:
                os.write(self._fd, data)
                self.appended += 1
        except OSError as e:
            raise AnalysisError(
                f"lineage append failed for window "
                f"{record.get('window')}: {e}"
            ) from e

    def sync(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.fsync(self._fd)
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None

    @staticmethod
    def read(path: str) -> list[dict]:
        """Parse a lineage log, tolerating (only) a torn final line."""
        import json

        out: list[dict] = []
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return out
        lines = raw.split(b"\n")
        tail = lines.pop()  # b"" after a complete final record
        for ln in lines:
            if not ln.strip():
                continue
            out.append(json.loads(ln))  # non-final damage IS corruption
        if tail.strip():
            # torn final append: ignore, exactly like the WAL's torn tail
            pass
        return out
