"""Tenancy plane: many tenants' rulesets on one mesh (ISSUE 16).

The paper's analysis semantics are embarrassingly parallel across
independent rulesets, so one serve process can host thousands of
firewall fleets ("tenants") on the same device mesh.  Three pieces:

- **Packing ladder + registry.**  Each tenant keeps its OWN key/gid
  universe (concatenating key spaces would move every CMS/HLL hash
  position and break bit-identity with solo runs).  Tenants are
  bucketed by their rule-count/ACL-count RUNGS — the same
  geometric-ladder trick runtime/coalesce.py uses for batch shapes — and
  each bucket stacks its members' padded rule tensors and register
  planes on a leading tenant axis.  One compiled step per bucket
  geometry serves every tenant in it.

- **Engine.**  :class:`TenantEngine` owns the per-bucket device stacks
  and dispatches one tenant's batch per device step
  (``parallel/step.py::make_tenant_step``): the step dynamically slices
  tenant ``tid``'s plane, runs the UNCHANGED flat core, and writes the
  plane back — so each tenant's registers evolve bit-identically to a
  solo run with the same chunk boundaries and salts (property-tested).
  The step is never ruleset-specialized: hot-reloading one tenant is a
  value update in one slice of a traced argument, no recompile, no
  stall for the others.

- **Router.**  Host-side: every ingested line is tagged with a tenant
  id by (in precedence order) an explicit ``@tenant <name> `` line
  prefix, the listener it arrived on, the syslog hostname map, or the
  manifest's default tenant.

The serve integration (per-tenant windows/reports/quarantine/reload,
fairness accounting, labeled /metrics) lives in runtime/tenantserve.py.
"""

from __future__ import annotations

import dataclasses
import json
import re
import time

import numpy as np

from ..errors import AnalysisError
from ..hostside.listener import LineQueue
from ..hostside.pack import PackedRuleset
from ..ops.match import RULE_BLOCK
from .wal import DEFAULT_TENANT

__all__ = [
    "DEFAULT_TENANT", "TENANT_TAG_PREFIX", "TenantSpec", "load_manifest",
    "rule_rung", "acl_rung", "tenant_rung", "bucket_key",
    "TenantRouter", "TenantLineQueue", "TenantTap", "TenantEngine",
]

#: Explicit in-band routing tag: a line beginning ``@tenant <name> `` is
#: routed to ``<name>`` with the tag stripped before parsing.  Wins over
#: listener binding and hostname mapping (an operator-injected override).
TENANT_TAG_PREFIX = "@tenant "

#: Tenant names travel in WAL records, prom labels, file paths, and URL
#: segments — keep them boring.  Bounded well under the WAL's 255-byte
#: record key limit.
_NAME_RE = re.compile(r"^[a-z0-9][a-z0-9_.-]{0,62}$")


def check_tenant_name(name: str) -> str:
    if not _NAME_RE.match(name or ""):
        raise AnalysisError(
            f"invalid tenant name {name!r}: want ^[a-z0-9][a-z0-9_.-]{{0,62}}$"
        )
    return name


# ---------------------------------------------------------------------------
# Manifest
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's manifest row (``serve --tenants manifest.json``)."""

    name: str
    ruleset: str  # packed-ruleset path prefix (pack.load_packed)
    listen: tuple[str, ...] = ()  # listener specs bound to THIS tenant
    hosts: tuple[str, ...] = ()  # syslog hostnames routed to this tenant
    default: bool = False  # route otherwise-unmatched lines here


def load_manifest(path: str) -> list[TenantSpec]:
    """Parse + validate a tenants manifest.

    ``{"tenants": [{"name": ..., "ruleset": ..., "listen": [...],
    "hosts": [...], "default": bool}, ...]}``.  Typed refusals for the
    ambiguities that would silently misroute: duplicate names, a
    hostname claimed by two tenants, more than one default.
    """
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise AnalysisError(f"cannot read tenants manifest {path!r}: {e}") from e
    rows = doc.get("tenants") if isinstance(doc, dict) else None
    if not isinstance(rows, list) or not rows:
        raise AnalysisError(
            f"tenants manifest {path!r} must hold a non-empty 'tenants' list"
        )
    specs: list[TenantSpec] = []
    seen_names: set[str] = set()
    seen_hosts: dict[str, str] = {}
    defaults: list[str] = []
    for i, row in enumerate(rows):
        if not isinstance(row, dict) or "name" not in row or "ruleset" not in row:
            raise AnalysisError(
                f"tenants[{i}] must be an object with 'name' and 'ruleset'"
            )
        name = check_tenant_name(str(row["name"]))
        if name in seen_names:
            raise AnalysisError(f"duplicate tenant name {name!r} in manifest")
        seen_names.add(name)
        hosts = tuple(str(h) for h in row.get("hosts", ()))
        for h in hosts:
            if h in seen_hosts:
                raise AnalysisError(
                    f"hostname {h!r} claimed by tenants {seen_hosts[h]!r} "
                    f"and {name!r} — routing would be ambiguous"
                )
            seen_hosts[h] = name
        if row.get("default"):
            defaults.append(name)
        specs.append(TenantSpec(
            name=name,
            ruleset=str(row["ruleset"]),
            listen=tuple(str(s) for s in row.get("listen", ())),
            hosts=hosts,
            default=bool(row.get("default", False)),
        ))
    if len(defaults) > 1:
        raise AnalysisError(
            f"manifest declares {len(defaults)} default tenants "
            f"({', '.join(defaults)}); at most one is allowed"
        )
    return specs


# ---------------------------------------------------------------------------
# Packing ladder (coalesce.py's geometric-rung trick, applied to rules)
# ---------------------------------------------------------------------------


def rule_rung(n_rules: int, rule_block: int = RULE_BLOCK) -> int:
    """Smallest ``rule_block * 2**i`` >= ``n_rules`` (geometric ladder).

    Bounding the distinct rule paddings bounds the distinct compiled
    step programs — exactly why coalesce buckets batch shapes.  Rungs
    stay RULE_BLOCK multiples so the bucket's stacked tensor feeds the
    unchanged blocked match kernel.
    """
    r = rule_block
    while r < max(n_rules, 1):
        r *= 2
    return r


def acl_rung(n_acls: int) -> int:
    """Smallest power of two >= ``n_acls`` (deny-key plane rung)."""
    a = 1
    while a < max(n_acls, 1):
        a *= 2
    return a


def tenant_rung(n_tenants: int) -> int:
    """Smallest power of two >= ``n_tenants`` (stack depth rung): a
    tenant joining a bucket restacks at most O(log T) times ever."""
    t = 1
    while t < max(n_tenants, 1):
        t *= 2
    return t


def bucket_key(packed: PackedRuleset, rule_block: int = RULE_BLOCK) -> tuple[int, int]:
    """(rule rung, ACL rung) — the bucket a packed ruleset lands in."""
    return rule_rung(packed.rules.shape[0], rule_block), acl_rung(packed.n_acls)


# ---------------------------------------------------------------------------
# Host-side routing
# ---------------------------------------------------------------------------


class TenantRouter:
    """Line -> tenant id, by explicit tag > listener > hostname > default.

    Pure host-side string work; the device step never sees routing.
    Unroutable lines return ``(None, line)`` and the caller accounts
    them (``lines_unrouted_total``) — routing must never silently guess.
    """

    def __init__(self, specs: list[TenantSpec]):
        self.names = [s.name for s in specs]
        self._known = set(self.names)
        self._host_map = {
            h: s.name for s in specs for h in s.hosts
        }
        self.default = next((s.name for s in specs if s.default), None)

    def route(self, line: str, listener_tenant: str | None = None
              ) -> tuple[str | None, str]:
        """Resolve one raw line; returns (tenant | None, line-sans-tag)."""
        if line.startswith(TENANT_TAG_PREFIX):
            rest = line[len(TENANT_TAG_PREFIX):]
            name, sep, body = rest.partition(" ")
            if sep and name in self._known:
                return name, body
            return None, line  # tagged for a tenant this process lacks
        if listener_tenant is not None:
            return listener_tenant, line
        host = self._syslog_host(line)
        if host is not None:
            hit = self._host_map.get(host)
            if hit is not None:
                return hit, line
        return self.default, line

    @staticmethod
    def _syslog_host(line: str):
        # the SAME token the parser resolves as the firewall name
        # (hostside/syslog.py::_TAG_RE group 1), so hostname routing and
        # gid resolution can never disagree about who sent the line
        from ..hostside.syslog import _TAG_RE

        m = _TAG_RE.search(line)
        return m.group(1) if m else None


class TenantLineQueue(LineQueue):
    """LineQueue whose entries carry the ingress tenant tag.

    Listeners bound to a tenant enqueue through a :class:`TenantTap`
    (the tag rides WITH the line, so routing never races the queue);
    untagged listeners enqueue with ``tag=None`` and the router decides
    at consume time.  Drop/receipt accounting is inherited unchanged —
    one shared bounded queue is the fairness boundary, and the per-
    tenant consume counters in tenantserve expose who filled it.
    """

    def put(self, line: str, tag: str | None = None) -> bool:  # type: ignore[override]
        t = time.monotonic()
        with self._lock:
            self.received += 1
            if len(self._q) >= self.capacity:
                self.dropped += 1
                return False
            self._q.append((line, t, tag))  # type: ignore[arg-type]
            self._ready.notify()
            return True

    def pop_ts(self, timeout: float = 0.2):
        got = self.pop_tagged(timeout)
        return None if got is None else (got[0], got[1])

    def pop_tagged(self, timeout: float = 0.2) -> tuple[str, float, str | None] | None:
        """Next line WITH receipt stamp AND ingress tenant tag."""
        with self._ready:
            if not self._q:
                self._ready.wait(timeout)
            if not self._q:
                return None
            return self._q.popleft()  # type: ignore[return-value]


class TenantTap:
    """Per-listener queue adapter stamping a fixed tenant tag.

    Listeners only ever call ``put`` / ``note_forced_drop`` /
    ``note_discarded`` (hostside/listener.py), so this duck-typed shim
    is the entire ingress-side routing hook: one shared queue, per-
    listener provenance.
    """

    def __init__(self, q: TenantLineQueue, tenant: str | None):
        self.q = q
        self.tenant = tenant

    def put(self, line: str) -> bool:
        return self.q.put(line, self.tenant)

    def note_forced_drop(self) -> None:
        self.q.note_forced_drop()

    def note_discarded(self, n: int = 1) -> None:
        self.q.note_discarded(n)


# ---------------------------------------------------------------------------
# Engine: per-bucket device stacks + the tenant step dispatch
# ---------------------------------------------------------------------------


def _pad_rules_to(rules: np.ndarray, r_pad: int) -> np.ndarray:
    from ..hostside.pack import NO_ACL, R_ACL, RULE_COLS

    out = np.zeros((r_pad, RULE_COLS), dtype=np.uint32)
    out[:, R_ACL] = NO_ACL  # padding rows can never match any line
    out[: rules.shape[0]] = rules
    return out


def _pad_deny_to(deny_key: np.ndarray, a_pad: int) -> np.ndarray:
    out = np.zeros(a_pad, dtype=np.uint32)
    out[: deny_key.shape[0]] = deny_key.astype(np.uint32)
    return out


class _Bucket:
    """One (rule rung, ACL rung) bucket: stacked tensors + its step."""

    __slots__ = (
        "r_pad", "a_pad", "t_pad", "names", "rules_t", "deny_t", "state",
        "step",
    )

    def __init__(self, r_pad: int, a_pad: int):
        self.r_pad = r_pad
        self.a_pad = a_pad
        self.t_pad = 0
        self.names: list[str | None] = []  # slot -> tenant (None = free)
        self.rules_t = None  # jax [T, r_pad, RULE_COLS]
        self.deny_t = None  # jax [T, a_pad]
        self.state = None  # AnalysisState, leaves [T, ...]
        self.step = None

    @property
    def n_keys(self) -> int:
        """The bucket's padded key universe (every member's rule keys
        and deny keys index strictly below it)."""
        return self.r_pad + self.a_pad


class TenantEngine:
    """Device-side tenancy: bucketed rule/register stacks, one step each.

    ``run_batch(name, batch, salt)`` steps ONE tenant's packed batch;
    callers (the tenant serve driver) interleave tenants freely because
    every register plane is tenant-sliced and the merge laws are
    unchanged.  Hot reload (:meth:`reload_tenant`) updates one slice of
    a traced rule argument — same executable, other tenants unaffected.
    """

    def __init__(
        self,
        mesh,
        cfg,
        rulesets: dict[str, PackedRuleset],
        rule_block: int = RULE_BLOCK,
    ):
        if not rulesets:
            raise AnalysisError("TenantEngine needs at least one tenant")
        self.mesh = mesh
        self.cfg = cfg
        self.rule_block = rule_block
        self.packed: dict[str, PackedRuleset] = {}
        self.buckets: dict[tuple[int, int], _Bucket] = {}
        self._slot: dict[str, tuple[tuple[int, int], int]] = {}
        # Batch construction: the final rung of every bucket is known up
        # front, so assemble each bucket's stacks host-side (numpy) and
        # ship them in ONE transfer per bucket.  Installing tenants one
        # at a time instead costs a per-slot ``.at[tid].set`` program
        # PER tenant (tid is baked into the jaxpr) — dozens of tiny XLA
        # compiles that dominate cold-start at 16+ tenants.
        import jax.numpy as jnp

        from ..hostside.pack import NO_ACL, R_ACL, RULE_COLS

        by_bucket: dict[tuple[int, int], list[tuple[str, PackedRuleset]]] = {}
        for name in sorted(rulesets):
            nm = check_tenant_name(name)
            self._check_v4_only(nm, rulesets[name])
            bkey = bucket_key(rulesets[name], rule_block)
            by_bucket.setdefault(bkey, []).append((nm, rulesets[name]))
        for bkey in sorted(by_bucket):
            members = by_bucket[bkey]
            bucket = _Bucket(*bkey)
            self.buckets[bkey] = bucket
            bucket.t_pad = tenant_rung(len(members))
            self._check_budget(bkey, bucket.t_pad)
            rules_np = np.zeros(
                (bucket.t_pad, bucket.r_pad, RULE_COLS), dtype=np.uint32
            )
            rules_np[:, :, R_ACL] = NO_ACL
            deny_np = np.zeros((bucket.t_pad, bucket.a_pad), dtype=np.uint32)
            for tid, (nm, packed) in enumerate(members):
                rules_np[tid] = _pad_rules_to(packed.rules, bucket.r_pad)
                deny_np[tid] = _pad_deny_to(packed.deny_key, bucket.a_pad)
                bucket.names.append(nm)
                self.packed[nm] = packed
                self._slot[nm] = (bkey, tid)
            bucket.rules_t = jnp.asarray(rules_np)
            bucket.deny_t = jnp.asarray(deny_np)
            bucket.state = self._zeros_stack(bucket)

    # -- assembly ---------------------------------------------------------
    def _check_v4_only(self, name: str, packed: PackedRuleset) -> None:
        if packed.rules6 is not None and packed.rules6.shape[0] > 0:
            raise AnalysisError(
                f"tenant {name!r}: IPv6 ACE rows are not supported on the "
                "tenancy plane yet (single-tenant serve handles v6); "
                "ROADMAP scope bound"
            )

    def _check_budget(self, bkey: tuple[int, int], t_pad: int) -> None:
        from ..models.pipeline import register_bytes

        n_keys = bkey[0] + bkey[1]
        per = sum(register_bytes(n_keys, self.cfg).values())
        budget = self.cfg.register_memory_budget_bytes
        if per * t_pad > budget:
            raise AnalysisError(
                f"tenant bucket {bkey} x {t_pad} slots needs "
                f"{per * t_pad} register bytes > budget {budget}; "
                "lower --hll-p/--cms-width or raise --register-budget-mb"
            )

    def _zeros_stack(self, bucket: _Bucket):
        import jax.numpy as jnp

        from ..models.pipeline import AnalysisState, init_state_host

        plane = init_state_host(bucket.n_keys, self.cfg)
        return AnalysisState(*(
            jnp.zeros((bucket.t_pad, *leaf.shape), dtype=leaf.dtype)
            for leaf in plane
        ))

    def _install(self, name: str, packed: PackedRuleset) -> None:
        """Place a tenant into its bucket (fresh zero register plane)."""
        self._check_v4_only(name, packed)
        import jax.numpy as jnp

        bkey = bucket_key(packed, self.rule_block)
        bucket = self.buckets.get(bkey)
        if bucket is None:
            bucket = _Bucket(*bkey)
            self.buckets[bkey] = bucket
        try:
            tid = bucket.names.index(None)  # reuse a freed slot
        except ValueError:
            tid = len(bucket.names)
            if tid >= bucket.t_pad:  # grow the stack one rung
                new_t = tenant_rung(tid + 1)
                self._check_budget(bkey, new_t)
                self._restack(bucket, new_t)
            bucket.names.append(None)
        rules = jnp.asarray(_pad_rules_to(packed.rules, bucket.r_pad))
        deny = jnp.asarray(_pad_deny_to(packed.deny_key, bucket.a_pad))
        bucket.rules_t = bucket.rules_t.at[tid].set(rules)
        bucket.deny_t = bucket.deny_t.at[tid].set(deny)
        bucket.names[tid] = name
        self.packed[name] = packed
        self._slot[name] = (bkey, tid)
        self.zero_tenant(name)

    def _restack(self, bucket: _Bucket, new_t: int) -> None:
        """Grow a bucket's stacks to ``new_t`` slots (value-preserving).

        Pure array concatenation — no other tenant's slice moves, no
        flush, no recompile of OTHER buckets; the bucket's own step
        recompiles once for the new stack depth (the geometric rung
        bounds that to O(log T) compiles over the bucket's lifetime).
        """
        import jax.numpy as jnp

        from ..hostside.pack import NO_ACL, R_ACL, RULE_COLS
        from ..models.pipeline import AnalysisState
        from . import faults

        # chaos seam: a mid-restack failure must leave the old stacks
        # (and every other tenant's live registers) fully intact
        faults.fire("tenancy.reload.restack")
        old_t = bucket.t_pad
        bucket.t_pad = new_t
        if old_t == 0:
            pad_rules = np.zeros((new_t, bucket.r_pad, RULE_COLS), dtype=np.uint32)
            pad_rules[:, :, R_ACL] = NO_ACL
            bucket.rules_t = jnp.asarray(pad_rules)
            bucket.deny_t = jnp.zeros((new_t, bucket.a_pad), dtype=jnp.uint32)
            bucket.state = self._zeros_stack(bucket)
            return
        grow = new_t - old_t
        pad_rules = np.zeros((grow, bucket.r_pad, RULE_COLS), dtype=np.uint32)
        pad_rules[:, :, R_ACL] = NO_ACL
        bucket.rules_t = jnp.concatenate(
            [bucket.rules_t, jnp.asarray(pad_rules)], axis=0
        )
        bucket.deny_t = jnp.concatenate(
            [bucket.deny_t, jnp.zeros((grow, bucket.a_pad), dtype=jnp.uint32)],
            axis=0,
        )
        bucket.state = AnalysisState(*(
            jnp.concatenate(
                [leaf, jnp.zeros((grow, *leaf.shape[1:]), dtype=leaf.dtype)],
                axis=0,
            )
            for leaf in bucket.state
        ))
        bucket.step = None  # stack depth changed; rebuild lazily

    # -- introspection ----------------------------------------------------
    def tenants(self) -> list[str]:
        return sorted(self._slot)

    def bucket_of(self, name: str) -> _Bucket:
        return self.buckets[self._slot[name][0]]

    def slot_of(self, name: str) -> int:
        return self._slot[name][1]

    def describe(self) -> dict:
        """Registry image for /tenants + the flight recorder cursor."""
        return {
            "tenants": {
                name: {
                    "bucket": list(bkey), "slot": tid,
                    "n_rules": int(self.packed[name].rules.shape[0]),
                    "n_keys": int(self.packed[name].n_keys),
                }
                for name, (bkey, tid) in sorted(self._slot.items())
            },
            "buckets": {
                f"{r}x{a}": {
                    "rule_rung": r, "acl_rung": a, "slots": b.t_pad,
                    "occupied": sum(1 for n in b.names if n is not None),
                }
                for (r, a), b in sorted(self.buckets.items())
            },
        }

    # -- the hot path -----------------------------------------------------
    def run_batch(self, name: str, batch: np.ndarray, salt: int = 0):
        """Step one tenant's working batch ``[TUPLE_COLS, B]``; returns
        the host-bound ChunkOut (top-K candidates) for the caller's
        tracker.  The bucket's register stack updates in place."""
        from ..hostside import pack as pack_mod
        from ..parallel import mesh as mesh_lib
        from ..parallel.step import make_tenant_step

        bkey, tid = self._slot[name]
        bucket = self.buckets[bkey]
        if bucket.step is None:
            bucket.step = make_tenant_step(
                self.mesh, self.cfg, bucket.n_keys, self.rule_block
            )
        wire = pack_mod.compact_batch(batch)
        dev = mesh_lib.shard_batch(self.mesh, wire)
        ruleset = self._device_ruleset(bucket)
        bucket.state, out = bucket.step(bucket.state, ruleset, dev, tid, salt)
        return out

    @staticmethod
    def _device_ruleset(bucket: _Bucket):
        from ..models.pipeline import DeviceRulesetTenant

        return DeviceRulesetTenant(
            rules_t=bucket.rules_t, deny_key_t=bucket.deny_t
        )

    # -- per-tenant register plane I/O ------------------------------------
    def host_arrays(self, name: str) -> dict[str, np.ndarray]:
        """Fetch ONE tenant's register plane, sliced to ITS key universe
        (bit-identical to a solo run's state_to_host)."""
        import jax

        from ..models.pipeline import AnalysisState

        bkey, tid = self._slot[name]
        bucket = self.buckets[bkey]
        k = self.packed[name].n_keys
        out = {}
        for field, leaf in zip(AnalysisState._fields, bucket.state):
            arr = np.asarray(jax.device_get(leaf[tid]))
            if field in ("counts_lo", "counts_hi", "hll"):
                arr = arr[:k].copy()
            out[field] = arr
        return out

    def set_arrays(self, name: str, arrays: dict[str, np.ndarray]) -> None:
        """Write a tenant's register plane back (checkpoint restore /
        post-migration reload), padding key-indexed files to the rung."""
        import jax.numpy as jnp

        from ..models.pipeline import AnalysisState

        bkey, tid = self._slot[name]
        bucket = self.buckets[bkey]
        leaves = []
        for field, leaf in zip(AnalysisState._fields, bucket.state):
            arr = np.asarray(arrays[field], dtype=np.uint32)
            if field in ("counts_lo", "counts_hi", "hll"):
                pad = np.zeros(leaf.shape[1:], dtype=np.uint32)
                pad[: arr.shape[0]] = arr
                arr = pad
            leaves.append(leaf.at[tid].set(jnp.asarray(arr)))
        bucket.state = AnalysisState(*leaves)

    def zero_tenant(self, name: str) -> None:
        """Zero one tenant's register plane (window rotation)."""
        from ..models.pipeline import AnalysisState

        bkey, tid = self._slot[name]
        bucket = self.buckets[bkey]
        bucket.state = AnalysisState(*(
            leaf.at[tid].set(0) for leaf in bucket.state
        ))

    # -- reload -----------------------------------------------------------
    def reload_tenant(self, name: str, packed: PackedRuleset) -> None:
        """Atomically swap one tenant's rule tensor (register plane is
        the CALLER's to migrate via host_arrays/set_arrays around this).

        Same rungs: an in-place slice update of the traced rule stack —
        the compiled step is untouched, so no other tenant even
        observes the reload.  Rung change: the tenant moves buckets
        (its old slot frees); only the destination bucket's step can
        (re)compile, and only when the move grows a stack.
        """
        import jax.numpy as jnp

        self._check_v4_only(name, packed)
        if name not in self._slot:
            raise AnalysisError(f"unknown tenant {name!r}")
        old_key, tid = self._slot[name]
        new_key = bucket_key(packed, self.rule_block)
        if new_key == old_key:
            bucket = self.buckets[old_key]
            rules = jnp.asarray(_pad_rules_to(packed.rules, bucket.r_pad))
            deny = jnp.asarray(_pad_deny_to(packed.deny_key, bucket.a_pad))
            bucket.rules_t = bucket.rules_t.at[tid].set(rules)
            bucket.deny_t = bucket.deny_t.at[tid].set(deny)
            self.packed[name] = packed
            return
        # bucket move: free the old slot, install into the new rung
        old_bucket = self.buckets[old_key]
        self.zero_tenant(name)
        old_bucket.names[tid] = None
        del self._slot[name]
        del self.packed[name]
        self._install(name, packed)
