"""Deterministic fault injection + stall watchdog (the chaos tier).

Mature distributed stacks treat failure schedules as a first-class,
seeded test input (the fault-injection / chaos-soak pattern in
PAPERS.md's elastic-training and MapReduce-lineage entries); until now
this repo exercised its recovery paths only through a handful of
hand-scripted kill-one tests.  This module makes failure a *scheduled*
input:

- **Sites.**  Named fault points threaded through the hot paths
  (:data:`SITES`): feeder worker crash/stall, prefetch producer
  exception and queue stall, torn checkpoint/manifest writes, heartbeat
  drop and elastic worker death, corrupt wire block, device_put failure.
  Each is a single :func:`fire` call that is a no-op unless a plan is
  armed — the disarmed cost is one module-global ``None`` check, so the
  sites stay in production code permanently (measured: no regression on
  the BENCH_r06 pipeline-efficiency path).

- **Plans.**  A :class:`FaultPlan` maps sites to :class:`FaultSpec`\\ s
  (*fire on the Nth hit of this site*; the transient form ``site@N:k``
  fires on hits N..N+k-1 — k consecutive failures, then the fault
  clears, which is how the chaos harness proves the retry engine
  recovers bit-identical rather than merely that aborts are typed).
  Plans are deterministic and
  serializable (``"site@N,site@N:k,seed=S"``), armable from the CLI
  (``run --fault-plan``), config (``AnalysisConfig.fault_plan``), or the
  ``RA_FAULT_PLAN`` environment variable — the env var is how a plan
  reaches spawned children (feeder worker processes, elastic generation
  workers), since :func:`arm` exports it and ``spawn`` inherits the
  environment.  :meth:`FaultPlan.random` derives a schedule from a seed,
  so chaos suites can sweep seeds and still replay any failure exactly.

- **Watchdog.**  The stall half of the chaos invariant: every wait in
  the ingest/feed tiers is bounded by :func:`default_stall_timeout`
  (overridable per run via ``AnalysisConfig.stall_timeout_sec``), and a
  stage that stops advancing without dying escalates to a typed
  :class:`~..errors.StallError` abort instead of an indefinite wedge.
  The elastic supervisor's existing bounds (STALE_SEC heartbeat staleness,
  KILL_GRACE_SEC wedged-worker kill, FORM_TIMEOUT_SEC formation) are the
  distributed members of the same tier.

The system-level invariant the chaos harness (tests/test_chaos.py)
asserts on top: under ANY armed schedule, a run either produces a report
bit-identical to the fault-free baseline or exits with a typed
``AnalysisError`` subclass — never a hang, never a silent wrong answer,
never a leaked thread/process/rendezvous file.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import random
import threading
import time

from ..errors import AnalysisError, InjectedFault

#: Environment variable carrying the armed plan spec to child processes.
ENV_VAR = "RA_FAULT_PLAN"

#: Default bound on "a pipeline stage made no progress" before the
#: watchdog escalates to StallError.  Generous: a legitimately slow
#: stage (cold NFS, giant descriptor) only has to advance once per
#: window, not finish.
_DEFAULT_STALL_SEC = 300.0

#: Hard cap on an injected stall that nobody releases (the watchdog
#: should fire long before; this only guarantees a daemon thread in a
#: crashing process cannot spin forever).
_STALL_CAP_SEC = 600.0

#: Registered fault sites: name -> (action, description).  The action is
#: intrinsic to the site (each site simulates one concrete failure);
#: plans choose WHICH sites fire and on which hit, not what they do.
#:
#:   raise   raise InjectedFault at the site
#:   stall   stop advancing (released by disarm / the caller's stop
#:           event); the stage's watchdog must escalate to StallError
#:   crash   os._exit — abrupt process death, no teardown (OOM-kill /
#:           node-death analog; the exit code is site-specific)
#:   torn    truncate the file the site just wrote, then raise — a
#:           crash mid-save with a partial write on disk
#:   corrupt return a damaged copy of the site's payload (the caller
#:           supplies the site-specific corruptor)
SITES: dict[str, tuple[str, str]] = {
    "feeder.worker.crash": (
        "crash", "a parse feed worker process dies abruptly (OOM-kill analog)"),
    "feeder.worker.stall": (
        "stall", "a feed worker wedges mid-parse and stops completing batches"),
    "feeder.ring.stall": (
        "stall", "a per-chip ring producer wedges before filling its "
        "slot; the ring runs dry and the coordinator's watchdog must "
        "bound the starved chip to a typed abort, never a hang"),
    "ingest.producer.raise": (
        "raise", "the prefetch producer thread fails mid-batch"),
    "ingest.queue.stall": (
        "stall", "the prefetch producer wedges; the bounded queue runs dry"),
    "ingest.coalesce.fail": (
        "raise", "the flow-coalescing compactor fails mid-batch (host "
        "OOM / native-library fault analog); a half-built weighted batch "
        "must never reach the device"),
    "checkpoint.torn_state": (
        "torn", "crash mid-save after a partial register-file write"),
    "checkpoint.torn_manifest": (
        "torn", "crash mid-save after a partial manifest write"),
    "elastic.heartbeat.drop": (
        "stall", "a member's rendezvous heartbeat stops (partition/freeze)"),
    "elastic.worker.die": (
        "crash", "an elastic analysis worker dies mid-collective (node death)"),
    "stream.wire.corrupt": (
        "corrupt", "a wire-format block arrives bit-flipped from storage"),
    "stream.device_put.fail": (
        "raise", "host->device transfer fails (XLA runtime error analog)"),
    "listener.drop": (
        "corrupt", "the serve listener tier loses one received line "
        "(kernel buffer overrun analog); MUST surface as an explicit "
        "drop count + WindowIncomplete marker, never a silent zero-hit "
        "window"),
    "listener.stall": (
        "stall", "a serve listener thread wedges mid-receive and stops "
        "delivering lines (frozen relay/socket analog)"),
    "reload.midbatch": (
        "raise", "a live ruleset reload fails mid-swap; the old rule "
        "tensor and counters must stay intact (atomic reload)"),
    "tenancy.reload.restack": (
        "raise", "a tenant bucket restack (stack-depth rung growth at "
        "install/reload) fails mid-copy; the old stacks and every other "
        "tenant's live registers must stay intact"),
    "autoscale.decide": (
        "raise", "the autoscale policy engine fails at the moment a "
        "scale decision is issued (decide->actuate seam); the run must "
        "abort typed or keep serving at the old world, never actuate a "
        "half-issued scale event"),
    "autoscale.spawn": (
        "raise", "actuating a scale event fails (worker spawn / mesh "
        "re-formation error analog); registers and in-flight batches "
        "must survive intact — typed abort or bit-identical report"),
    "analyze.tile": (
        "raise", "a static-analysis pair tile fails mid-grid "
        "(runtime/staticanalysis.py); the analysis must abort typed — a "
        "partial verdict table must NEVER be published as complete, and "
        "a serve reload's re-analysis failing must leave the previous "
        "complete verdict set serving"),
    "devprof.capture": (
        "raise", "the in-process jax.profiler capture window fails at "
        "its start or stop seam (runtime/devprof.py); the run must end "
        "in a typed abort or complete as a clean no-trace run with a "
        "bit-identical report — never a hang, a half-written "
        "devprof.json, or a corrupted report"),
    "stream.wire.read.fail": (
        "raise", "wire-file / convert-manifest open or header read IO "
        "fails (cold-NFS hiccup analog); the wire.read retry site "
        "absorbs a transient burst, a persistent failure escalates to "
        "the existing typed feed abort"),
    "listener.bind.fail": (
        "raise", "a serve listener socket bind fails (TIME_WAIT rebind "
        "analog); the listener.bind retry site waits it out with "
        "backoff, persistent failure is the documented clean bind "
        "error"),
    "listener.accept.fail": (
        "raise", "a serve listener's receive loop throws mid-iteration "
        "(socket/driver hiccup analog); the listener.accept retry site "
        "re-enters the loop, exhaustion records the error and marks "
        "the listener dead (windows incomplete, all-dead aborts typed)"),
    "serve.publish.fail": (
        "raise", "serve report publication to disk fails (full/readonly "
        "volume analog); the serve.publish retry site absorbs a "
        "transient burst, exhaustion DEGRADES the publisher subsystem "
        "(/health names it, in-memory endpoints keep serving) instead "
        "of aborting ingest"),
    "metrics.snapshot.fail": (
        "raise", "the metrics snapshotter's periodic tick fails "
        "(unwritable metrics file analog); the tick error is counted "
        "and the ra-metrics thread keeps running — serve marks the "
        "metrics subsystem degraded and recovery re-arms it"),
    "lease.acquire": (
        "raise", "the distributed-serve supervisor lease cannot be "
        "claimed at startup (unwritable lease dir / storage fault "
        "analog); the supervisor must abort typed before spawning any "
        "ingest host, never publish without a fencing term"),
    "lease.renew": (
        "raise", "the lease-holder's heartbeat renewal fails and stays "
        "failed (partition / storage-freeze analog); the holder must "
        "self-fence within the lease TTL — stop publishing BEFORE a "
        "successor can win the lease — so a split brain can never "
        "double-publish one window id"),
    "dist.epoch.spool": (
        "raise", "a host's durable epoch-spool append fails (full / "
        "readonly volume analog); the host marks the spool subsystem "
        "degraded and keeps ingesting+shipping — losing durability is "
        "visible /health evidence, never a silent service stop"),
    "dist.epoch.ship": (
        "raise", "shipping a window epoch to the merge supervisor "
        "fails (severed host-tier connection / partition analog); the "
        "dist.epoch.ship retry site absorbs a transient burst, "
        "exhaustion parks the epoch in the partition backlog (degraded "
        "``partition:<rank>``) for heal-time reconciliation — the "
        "spooled copy survives either way"),
    "lineage.append": (
        "raise", "appending a published window's lineage record to "
        "lineage.jsonl fails (full volume / fd-revoked analog); the "
        "append is a CORE publication step — the serve loop aborts "
        "typed rather than publish a window without provenance, and "
        "the single-write O_APPEND discipline means the log holds only "
        "complete records (a torn final line reads as absent, never as "
        "corruption)"),
    "epochstore.spill": (
        "raise", "spilling a rotated window into the durable epoch "
        "store fails (full / readonly volume analog) BEFORE any bytes "
        "land; serve marks the epoch_store subsystem degraded and keeps "
        "publishing — losing history is visible /health + /lineage "
        "frontier evidence, never a torn store or a silent stop"),
    "epochstore.compact": (
        "crash", "SIGKILL at the worst instant of segment-tree "
        "compaction: after the pair is chosen, before the merged "
        "summary node is appended.  Compaction is append-then-link "
        "(the O_APPEND record IS the link), so the store must reopen "
        "readable with zero lost epochs and repair-at-open must "
        "rebuild the missing summary from its intact children"),
}


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled failure: ``site`` fires on hits ``at..at+count-1``.

    ``count == 1`` is the historical single-shot form; ``count > 1`` is
    the *transient* mode (``site@N:k`` in the plan grammar): the site
    fails k consecutive times and then clears — the shape a retry policy
    must survive, and the shape that proves budget exhaustion when k
    exceeds the site's attempt bound.
    """

    site: str
    at: int = 1
    count: int = 1

    def __post_init__(self) -> None:
        if self.site not in SITES:
            raise AnalysisError(
                f"unknown fault site {self.site!r}; registered sites: "
                f"{', '.join(sorted(SITES))}"
            )
        if self.at < 1:
            raise AnalysisError(f"fault hit count must be >= 1, got {self.at}")
        if self.count < 1:
            raise AnalysisError(
                f"fault consecutive-fire count must be >= 1, got {self.count}"
            )

    @property
    def action(self) -> str:
        return SITES[self.site][0]

    def fires_on(self, n: int) -> bool:
        return self.at <= n < self.at + self.count


class FaultPlan:
    """A deterministic failure schedule: {site -> FaultSpec} + seed.

    The seed feeds the ``corrupt`` action's bit-flip choices (and is
    recorded in the serialized form) so an armed plan replays the exact
    same damage every run.
    """

    def __init__(self, specs: dict[str, FaultSpec] | list[FaultSpec], seed: int = 0):
        if isinstance(specs, dict):
            specs = list(specs.values())
        self.specs: dict[str, FaultSpec] = {s.site: s for s in specs}
        self.seed = int(seed)
        #: set on disarm: releases every in-flight injected stall
        self.released = threading.Event()

    # -- serialization --------------------------------------------------
    def to_str(self) -> str:
        parts = [
            f"{s.site}@{s.at}" + (f":{s.count}" if s.count > 1 else "")
            for s in self.specs.values()
        ]
        if self.seed:
            parts.append(f"seed={self.seed}")
        return ",".join(parts)

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        """Inverse of :meth:`to_str` (``"site@N,site@N:k,seed=S"``)."""
        specs: list[FaultSpec] = []
        seed = 0
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            if part.startswith("seed="):
                try:
                    seed = int(part[5:])
                except ValueError as e:
                    raise AnalysisError(f"bad fault-plan seed {part!r}") from e
                continue
            site, _, at = part.partition("@")
            at, _, count = at.partition(":")
            try:
                specs.append(FaultSpec(
                    site, int(at) if at else 1, int(count) if count else 1
                ))
            except ValueError as e:
                raise AnalysisError(
                    f"bad fault-plan entry {part!r} (want site@N or site@N:k)"
                ) from e
        if not specs:
            raise AnalysisError(f"fault plan {text!r} names no sites")
        return cls(specs, seed=seed)

    @classmethod
    def random(
        cls,
        seed: int,
        sites: list[str] | None = None,
        n_faults: int = 1,
        max_at: int = 4,
    ) -> "FaultPlan":
        """Seeded schedule: ``n_faults`` distinct sites at random hits.

        Deterministic in ``seed`` — the chaos suites sweep seeds and can
        replay any failing schedule exactly from its number alone.
        """
        rng = random.Random(seed)
        pool = sorted(sites) if sites is not None else sorted(SITES)
        picked = rng.sample(pool, min(n_faults, len(pool)))
        return cls(
            [FaultSpec(s, rng.randint(1, max_at)) for s in picked], seed=seed
        )

    def __repr__(self) -> str:  # readable failures in chaos assertions
        return f"FaultPlan({self.to_str()!r})"


# ---------------------------------------------------------------------------
# Module arming state.  `_plan is None` is the production fast path; the
# env check runs at most once per process so spawned children (which
# inherit RA_FAULT_PLAN) arm themselves lazily on their first site hit.
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_plan: FaultPlan | None = None
_hits: dict[str, int] = {}
_env_checked = False
_env_exported = False


def arm(plan: FaultPlan, *, export_env: bool = True) -> None:
    """Arm ``plan`` process-wide; hit counters reset.

    ``export_env`` also publishes the spec to :data:`ENV_VAR` so worker
    processes spawned while armed inherit the schedule.
    """
    global _plan, _env_checked, _env_exported
    with _lock:
        _plan = plan
        _hits.clear()
        _env_checked = True
        if export_env:
            os.environ[ENV_VAR] = plan.to_str()
            _env_exported = True


def disarm() -> None:
    """Disarm and release any in-flight injected stalls."""
    global _plan, _env_exported
    with _lock:
        if _plan is not None:
            _plan.released.set()
        _plan = None
        _hits.clear()
        if _env_exported:
            os.environ.pop(ENV_VAR, None)
            _env_exported = False


def active_plan() -> FaultPlan | None:
    return _plan


@contextlib.contextmanager
def armed(plan: FaultPlan):
    """``with faults.armed(plan): ...`` — arm for the block, then disarm."""
    arm(plan)
    try:
        yield plan
    finally:
        disarm()


def arm_spec(spec: str) -> bool:
    """Arm from a serialized spec if not already armed with the same one.

    Idempotent so both the CLI and the drivers may call it with the same
    ``AnalysisConfig.fault_plan`` without resetting hit counters mid-run.
    Returns True when THIS call armed the plan — the caller then owns
    disarming it at run end, so an armed schedule (and its RA_FAULT_PLAN
    export) never leaks into a later run in the same process.  An empty
    spec never disarms ambient arming (the chaos harness arms around the
    driver call with config untouched).
    """
    if not spec:
        return False
    cur = _plan
    if cur is not None and cur.to_str() == FaultPlan.parse(spec).to_str():
        return False
    arm(FaultPlan.parse(spec))
    return True


def default_stall_timeout() -> float:
    """Watchdog bound on a stage making no progress (RA_STALL_TIMEOUT)."""
    try:
        t = float(os.environ.get("RA_STALL_TIMEOUT", _DEFAULT_STALL_SEC))
    except ValueError:
        t = _DEFAULT_STALL_SEC
    return t if t > 0 else _DEFAULT_STALL_SEC


def _check_env() -> FaultPlan | None:
    """One-time lazy arm from the environment (spawned children)."""
    global _env_checked
    with _lock:
        if _env_checked:
            return _plan
        _env_checked = True
    spec = os.environ.get(ENV_VAR, "")
    if spec:
        # don't re-export: the var is already in our (inherited) env
        arm(FaultPlan.parse(spec), export_env=False)
    return _plan


def _stall(plan: FaultPlan, stop: threading.Event | None) -> None:
    """Stop advancing until released (disarm) or the caller's stop event.

    Polling two events beats wedging on one: the injecting test releases
    via disarm, a shutting-down stage releases via its own stop signal,
    and the absolute cap guarantees a daemon thread can never spin past
    process teardown.
    """
    deadline = time.monotonic() + _STALL_CAP_SEC
    while time.monotonic() < deadline:
        if plan.released.is_set():
            return
        if stop is not None and stop.is_set():
            return
        time.sleep(0.05)


def fire(
    site: str,
    *,
    stop: threading.Event | None = None,
    payload=None,
    path: str | None = None,
    corrupt=None,
    crash_rc: int = 1,
):
    """The fault point: no-op (returning ``payload``) unless armed.

    Callers thread site-specific context: ``stop`` lets an injected
    stall release when the stage shuts down, ``path`` is the file a
    ``torn`` site truncates, ``corrupt`` is the payload-damaging
    callback a ``corrupt`` site applies (seeded rng supplied), and
    ``crash_rc`` is the exit code of a ``crash`` site.
    """
    plan = _plan
    if plan is None:
        if _env_checked:
            return payload
        plan = _check_env()
        if plan is None:
            return payload
    spec = plan.specs.get(site)
    if spec is None:
        return payload
    with _lock:
        _hits[site] = n = _hits.get(site, 0) + 1
    if not spec.fires_on(n):
        return payload
    action = spec.action
    # mark the firing on the trace timeline BEFORE acting: the per-event
    # flush means even a `crash` (os._exit) or `torn` site leaves its
    # instant in this process's shard, so a merged chaos trace shows
    # exactly where every injected failure landed
    from . import obs

    obs.instant(f"fault.{site}", args={"action": action, "hit": n})
    if action == "raise":
        raise InjectedFault(f"injected fault: {site} (hit {n})")
    if action == "stall":
        _stall(plan, stop)
        # the stall was released (watchdog fired / stage shut down /
        # plan disarmed): terminate this stage's work item loudly so it
        # cannot resume half-done
        raise InjectedFault(f"injected stall released: {site} (hit {n})")
    if action == "crash":
        # the flight recorder's LAST chance: os._exit skips every
        # excepthook/finally, so the ring (which already holds the
        # fault.<site> instant flushed above) dumps here or never —
        # exactly what a merged postmortem needs to name the dead worker
        from . import flightrec

        flightrec.dump("crash", error=f"injected crash: {site} (hit {n})")
        os._exit(crash_rc)
    if action == "torn":
        if path is not None:
            try:
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    f.truncate(max(1, size // 2))
            except OSError:
                pass  # the raise below still simulates the crash
        raise InjectedFault(f"injected torn write: {site} ({path})")
    if action == "corrupt":
        if corrupt is None or payload is None:
            raise InjectedFault(f"injected corruption: {site} (hit {n})")
        rng = random.Random((plan.seed << 16) ^ (n * 2654435761))
        return corrupt(payload, rng)
    raise AnalysisError(f"fault site {site} has unknown action {action!r}")
