"""Streaming driver: host text -> packed batches -> fused device steps.

The rebuild of the reference's job loop (SURVEY.md §4.2): where Hadoop
splits HDFS input across mapper processes, this driver cuts the unbounded
log stream into fixed-size batches (constant device memory, one compiled
program — SURVEY.md §6 "long-context" note), packs them on host, and feeds
the jitted analysis step.

Overlap comes from JAX's async dispatch: ``step`` returns immediately with
futures, so host parsing of chunk N+1 runs while the device crunches chunk
N.  Top-K candidates drain through a short lag queue so fetching them
never synchronises the host with the in-flight chunk.
"""

from __future__ import annotations

import os
import time
from collections import deque
from collections.abc import Iterable, Iterator

import jax
import numpy as np

from ..config import AnalysisConfig
from ..hostside import pack as pack_mod
from ..hostside.pack import T_VALID, TUPLE_COLS, LinePacker, PackedRuleset
from ..hostside.syslog import parse_line
from ..models import pipeline
from ..ops.topk import TopKTracker
from . import devprof, faults, obs


_SENTINEL = object()


def _arm_retry(cfg: AnalysisConfig) -> None:
    """Arm the retry/backoff table for one driver run (DESIGN §19).

    Called at the PUBLIC driver entries, before any source construction
    — the wire reader's open IO is itself a retry seam, and its attempts
    must land in this run's freshly-reset counters.  The flight
    recorder (DESIGN §20) arms here too when the config names a
    blackbox directory, so library callers get the same always-on
    forensics the CLI wires up.
    """
    from . import flightrec, retrypolicy

    retrypolicy.configure(cfg.retry_policy)
    if cfg.blackbox_dir:
        flightrec.arm(cfg.blackbox_dir, role="main")


def chunked(it: Iterable[str], size: int) -> Iterator[list[str]]:
    buf: list[str] = []
    for x in it:
        buf.append(x)
        if len(buf) == size:
            yield buf
            buf = []
    if buf:
        yield buf


class LineBatcher:
    """Push-based core of the text batching rules.

    Extracted from :class:`_TextSource` so the always-on serve loop
    (runtime/serve.py) forms batches under EXACTLY the batch drivers'
    boundary rules — the same early close when a dual-evaluation line
    would overflow, the same ``(None, n_raw)`` zero-valid batches, the
    same v6 side channel and capped digest map.  Identical boundaries
    are what make a per-window serve report bit-identical to an offline
    ``run_stream`` over the same window's lines (talker candidates are
    the one chunk-boundary-sensitive statistic; registers never are).

    ``push`` returns the ``(batch, n_raw)`` events the line completed
    (possibly empty); ``flush`` closes the partial batch at a window
    rotation or end of stream.
    """

    def __init__(
        self,
        packer: LinePacker,
        has_v6: bool,
        v6rows: list,
        v6_digests: dict[int, int],
        batch_size: int,
    ):
        self.packer = packer
        self._has_v6 = has_v6
        self._v6rows = v6rows
        self._digests = v6_digests
        self._batch = batch_size
        self._out = np.zeros((TUPLE_COLS, batch_size), dtype=np.uint32)
        self._fill = 0
        self.raw = 0  # raw lines assigned to the open batch

    def _emit(self) -> tuple[np.ndarray | None, int]:
        ev = ((self._out if self._fill else None), self.raw)
        self._out = np.zeros((TUPLE_COLS, self._batch), dtype=np.uint32)
        self._fill = 0
        self.raw = 0
        return ev

    def push(self, line: str) -> list[tuple[np.ndarray | None, int]]:
        events: list[tuple[np.ndarray | None, int]] = []
        packer = self.packer
        p = parse_line(line)
        gids = [] if p is None else packer.resolve_gids(p)
        if gids and p.family == 6:
            if not self._has_v6:
                # v6 traffic vs a pure-v4 ruleset: counted skip
                gids = []
            else:
                s = pack_mod.u128_limbs(p.src)
                d = pack_mod.u128_limbs(p.dst)
                for gid in gids:
                    self._v6rows.append(
                        (gid, p.proto, *s, p.sport, *d, p.dport, 1)
                    )
                dig = self._digests
                if len(dig) < pack_mod.V6_DIGEST_CAP:
                    dig.setdefault(pack_mod.fold_src32_host(p.src), p.src)
                packer.parsed += len(gids)
                self.raw += 1
                if self.raw == self._batch:
                    events.append(self._emit())
                return events
        if gids and self._fill + len(gids) > self._batch:
            events.append(self._emit())
        for gid in gids:
            self._out[:, self._fill] = (
                gid, p.proto, p.src, p.sport, p.dst, p.dport, 1
            )
            self._fill += 1
        packer.parsed += len(gids)
        if not gids:
            packer.skipped += 1
        self.raw += 1
        if self.raw == self._batch:
            events.append(self._emit())
        return events

    def flush(self) -> tuple[np.ndarray | None, int] | None:
        """Close the open partial batch (rotation / end of stream)."""
        if self.raw:
            return self._emit()
        return None


class _TextSource:
    """Batch source over an iterable of decoded lines (pure-Python parse).

    Batches are line-atomic: each holds a whole number of raw lines and at
    most ``batch_size`` tuple rows.  A batch normally covers exactly
    ``batch_size`` raw lines, but closes early when the next line's
    evaluations would not fit — a connection line evaluated against both
    an ``in`` and an ``out`` ACL emits two rows.  Counters update as lines
    are assigned to batches, so checkpoint snapshots (taken at batch
    boundaries) always agree with the batches actually emitted.

    A batch whose raw lines produced NO v4 tuple rows (a mostly-IPv6 or
    mostly-unparseable stretch of the corpus) is yielded as ``(None,
    n_raw)``: the driver accounts the raw lines (and drains any staged v6
    rows) without stepping an all-invalid device chunk — ADVICE r5 #3.
    """

    #: one shared knob for every source tier (see pack.V6_DIGEST_CAP)
    V6_DIGEST_CAP = pack_mod.V6_DIGEST_CAP

    def __init__(self, packed: PackedRuleset, lines: Iterable[str]):
        self.packer = LinePacker(packed)
        self._lines = lines
        self._has_v6 = packed.has_v6
        self._v6rows: list[tuple] = []
        #: fold_src32 digest -> 128-bit source int (report rendering)
        self.v6_digests: dict[int, int] = {}

    def set_counts(self, parsed: int, skipped: int) -> None:
        self.packer.parsed, self.packer.skipped = parsed, skipped

    def take_v6(self) -> list[tuple]:
        """Drain v6 tuple rows staged since the last call (driver-pulled).

        Drains IN PLACE: the LineBatcher holds a reference to this list,
        so rebinding the attribute would orphan its staging target and
        silently lose every later v6 row.
        """
        out = self._v6rows[:]
        del self._v6rows[:]
        return out

    def batches(self, skip_lines: int, batch_size: int) -> Iterator[tuple[np.ndarray, int]]:
        it = iter(self._lines)
        skipped_ok = 0
        for _ in range(skip_lines):
            if next(it, _SENTINEL) is _SENTINEL:
                break
            skipped_ok += 1
        if skipped_ok < skip_lines:
            from ..errors import ResumeInputMismatch

            raise ResumeInputMismatch(
                f"snapshot consumed {skip_lines} lines but the input "
                f"stream has only {skipped_ok}; wrong or truncated log input"
            )
        # v6 evaluations ride a side channel the driver pulls via take_v6
        # and steps through the v6 device program; they never consume v4
        # batch capacity (LineBatcher stages them into self._v6rows)
        b = LineBatcher(
            self.packer, self._has_v6, self._v6rows, self.v6_digests,
            batch_size,
        )
        for line in it:
            yield from b.push(line)
        tail = b.flush()
        if tail is not None:
            yield tail


class _PackedCounters:
    """parsed/skipped counters for sources that skip the text parse."""

    def __init__(self):
        self.parsed = 0
        self.skipped = 0


class _PackedSource:
    """Batch source over pre-packed ``[TUPLE_COLS, n]`` tuple arrays.

    The packed tier (SURVEY.md synth §"two tiers"): feeds the device
    pipeline at rates the text renderer can't reach — used by the scale
    benchmarks and the sketch-accuracy-at-scale validation.  Incoming
    arrays are re-chunked to exactly ``batch_size`` columns so chunk
    boundaries are identical to a text-path run over the same tuples.
    """

    def __init__(self, arrays: Iterable[np.ndarray]):
        self._arrays = arrays
        self.packer = _PackedCounters()

    def set_counts(self, parsed: int, skipped: int) -> None:
        self.packer.parsed, self.packer.skipped = parsed, skipped

    def batches(self, skip_lines: int, batch_size: int) -> Iterator[tuple[np.ndarray, int]]:
        buf = np.empty((TUPLE_COLS, batch_size), dtype=np.uint32)
        fill = 0
        to_skip = skip_lines
        for arr in self._arrays:
            pos = 0
            n = arr.shape[1]
            if to_skip:
                take = min(to_skip, n)
                pos += take
                to_skip -= take
            while pos < n:
                m = min(batch_size - fill, n - pos)
                buf[:, fill : fill + m] = arr[:, pos : pos + m]
                fill += m
                pos += m
                if fill == batch_size:
                    yield self._emit(buf, fill, batch_size)
                    fill = 0
        if to_skip:
            from ..errors import ResumeInputMismatch

            raise ResumeInputMismatch(
                f"snapshot consumed {skip_lines} lines but the packed input "
                f"ran short by {to_skip}"
            )
        if fill:
            yield self._emit(buf, fill, batch_size)

    def _emit(self, buf, fill, batch_size):
        # always a fresh array: the reusable fill buffer must not be
        # mutated under an in-flight async device_put of a prior chunk
        if fill == batch_size:
            out = buf.copy()
        else:
            out = np.zeros_like(buf)
            out[:, :fill] = buf[:, :fill]
        valid = int(out[T_VALID].sum())
        self.packer.parsed += valid
        self.packer.skipped += fill - valid
        return out, fill


def run_stream_packed(
    packed: PackedRuleset,
    arrays: Iterable[np.ndarray],
    cfg: AnalysisConfig,
    *,
    topk: int = 10,
    mesh=None,
    profile_dir: str | None = None,
    max_chunks: int | None = None,
):
    """Analyze pre-packed ``[TUPLE_COLS, n]`` tuple arrays (packed tier)."""
    _arm_retry(cfg)
    return _run_core(
        packed,
        _PackedSource(arrays),
        cfg,
        topk=topk,
        mesh=mesh,
        profile_dir=profile_dir,
        max_chunks=max_chunks,
    )


class _WireFileSource:
    """Batch source over on-disk ``.rawire`` files (hostside.wire).

    Yields wire-format ``[WIRE_COLS, batch]`` arrays directly —
    ``yields_wire`` tells the chunk loop to skip the host-side
    ``compact_batch`` (rows already crossed the converter in wire layout)
    and feed ``device_put`` straight from the mmap.  Counters come from
    the stored valid bits, and a stored row whose valid bit is clear —
    impossible from the converter, so necessarily block damage — is a
    typed ``WireCorrupt`` refusal rather than a silent skip-count.
    """

    yields_wire = True

    def __init__(self, packed: PackedRuleset, paths: list[str]):
        from ..hostside.wire import WireReader

        self.reader = WireReader(paths, packed)
        #: weighted (RAWIREv3) input: stored rows are coalesced unique
        #: tuples with a weights plane; parsed counters then count summed
        #: weights (true evaluations) while resume offsets stay in the
        #: stored-row unit this file defines
        self.weighted = self.reader.weighted
        self.yields_wire_weighted = self.weighted
        self.packer = _PackedCounters()
        #: fold digest -> 128-bit source (populated by batches6; report
        #: rendering of v6 talkers, same contract as _TextSource)
        self.v6_digests: dict[int, int] = {}

    def set_counts(self, parsed: int, skipped: int) -> None:
        self.packer.parsed, self.packer.skipped = parsed, skipped

    @property
    def n4_rows(self) -> int:
        return self.reader.n_rows

    @staticmethod
    def _check_chunk_weight(ws: int) -> None:
        """Refuse weighted chunks whose summed weights reach 2^32.

        The exact-counts accumulator's carry detection (counts.add64)
        assumes per-chunk deltas < 2^32; a plain chunk satisfies it by
        shape, but a weighted chunk's delta is the ORIGINAL line count
        behind its rows — an extraordinarily repetitive corpus could
        overflow the uint32 scatter undetected.  Loud refusal with a
        concrete fix beats a silently wrapped register.
        """
        from ..config import WEIGHTED_CHUNK_WEIGHT_LIMIT

        if ws >= WEIGHTED_CHUNK_WEIGHT_LIMIT:
            from ..errors import AnalysisError

            raise AnalysisError(
                f"weighted wire chunk carries {ws} original lines, which "
                "overflows the per-chunk uint32 count delta; re-convert "
                "with a smaller --block-rows (or run with a smaller "
                "--batch-size) so each chunk stays under 2^32 lines"
            )

    @staticmethod
    def _corrupt_wire(wire: np.ndarray, rng) -> np.ndarray:
        """Seeded storage-damage model for the ``stream.wire.corrupt`` site.

        Scrambles whole stored rows including their valid/meta word — the
        detectable corruption class the strict reader check below exists
        for.  (Damage confined to the address words of a still-valid row
        is indistinguishable from legitimate data without payload
        checksums; DESIGN §9 records that as the format's open item.)
        """
        from ..hostside.pack import W_META

        wire = wire.copy()  # never write through the read-only mmap view
        for _ in range(1 + rng.randrange(3)):
            j = rng.randrange(wire.shape[1])
            for w in range(wire.shape[0]):
                wire[w, j] ^= np.uint32(rng.getrandbits(32))
            wire[W_META, j] &= np.uint32(~(1 << 23) & 0xFFFFFFFF)
        return wire

    def batches(self, skip_lines: int, batch_size: int) -> Iterator[tuple[np.ndarray, int]]:
        from ..hostside.wire import sanity_check_valid_bits

        # resume offsets count the CONCATENATED v4-then-v6 row stream; an
        # offset past the v4 section means phase 1 is already complete.
        # The truncation/mismatch guard must live HERE against the total:
        # clamping alone would let a wrong or truncated wire input resume
        # "successfully" (iter_batches6's own guard never runs for
        # pure-v4 rulesets, where phase 2 is skipped entirely).
        total = self.reader.n_rows + self.reader.n6_rows
        if skip_lines > total:
            from ..errors import ResumeInputMismatch

            raise ResumeInputMismatch(
                f"snapshot consumed {skip_lines} rows but the wire input "
                f"has only {total}; wrong or truncated input"
            )
        skip4 = min(skip_lines, self.reader.n_rows)
        for wire, n in self.reader.iter_batches(skip4, batch_size):
            wire = faults.fire(
                "stream.wire.corrupt", payload=wire, corrupt=self._corrupt_wire
            )
            v, inv = sanity_check_valid_bits(wire)
            # padding columns of a short final batch are not stored rows
            pad = wire.shape[1] - n
            if inv > pad:
                # the converter stores ONLY valid evaluation rows, so a
                # stored row with the valid bit clear is block damage —
                # refuse loudly rather than silently skip-counting rows
                # of a corrupted production input (bit-identical-or-
                # typed-abort invariant, DESIGN §9)
                from ..errors import WireCorrupt

                raise WireCorrupt(
                    f"wire batch holds {inv - pad} stored row(s) with the "
                    "valid bit clear — the block was damaged after "
                    "conversion; re-run `ruleset-analyze convert` (or "
                    "repair storage) to proceed"
                )
            if self.weighted:
                # each stored row stands for `weight` original evaluations
                from ..hostside.pack import W_WEIGHT

                ws = int(wire[W_WEIGHT].sum())
                self._check_chunk_weight(ws)
                self.packer.parsed += ws
            else:
                self.packer.parsed += v
            self.packer.skipped += inv - pad
            yield wire, n

    def batches6(self, skip_rows6: int, batch_size: int) -> Iterator[tuple[np.ndarray, int]]:
        """Wire-v2 v6 section (consumed after the v4 stream — phase 2)."""
        import numpy as _np

        from ..hostside.pack import (
            W6_META, W6_SRC, fold_src32_np, limbs_u128,
        )

        cap = _TextSource.V6_DIGEST_CAP
        for w6, n in self.reader.iter_batches6(skip_rows6, batch_size):
            v = int(_np.count_nonzero(w6[W6_META] & _np.uint32(1 << 23)))
            if self.weighted:
                from ..hostside.pack import W6_WEIGHT

                ws6 = int(w6[W6_WEIGHT].sum())
                self._check_chunk_weight(ws6)
                self.packer.parsed += ws6
            else:
                self.packer.parsed += v
            self.packer.skipped += (w6.shape[1] - v) - (w6.shape[1] - n)
            if len(self.v6_digests) < cap and n:
                # digest -> address map for talker rendering: vectorized
                # fold + unique first, so the Python dict loop touches
                # each DISTINCT source once per batch, not each row
                limbs = w6[W6_SRC:W6_SRC + 4, :n]
                folds = fold_src32_np(limbs)
                _, idx = _np.unique(folds, return_index=True)
                idx.sort()  # stream order: first-seen wins at the cap,
                # matching _TextSource's documented contract
                dig = self.v6_digests
                for j in idx:
                    f = int(folds[j])
                    if f not in dig:
                        if len(dig) >= cap:
                            break
                        dig[f] = limbs_u128(*limbs[:, int(j)])
            yield w6, n

    def close(self) -> None:
        """Release the reader's mmaps/fds (called from _run_core's finally)."""
        self.reader.close()

    def totals_patch(self, complete: bool) -> dict:
        """True raw-line accounting once the whole input was consumed.

        Mid-stream, "lines" counts evaluation rows (the unit resume
        offsets use); after a complete pass the report states the
        original text totals recorded by the converter.
        """
        if not complete:
            return {"wire_rows_only": True}
        out = {
            "lines_total": self.reader.raw_lines,
            "lines_skipped": self.reader.n_skipped + self.packer.skipped,
            "wire_rows": self.reader.n_rows + self.reader.n6_rows,
        }
        if self.weighted:
            # stored rows are coalesced: state the true evaluation count
            # and the file's compaction ratio alongside
            out["wire_evals"] = self.reader.n_evals
            out["wire_weighted"] = True
        return out


def run_stream_wire(
    packed: PackedRuleset,
    paths: str | list[str],
    cfg: AnalysisConfig,
    *,
    topk: int = 10,
    mesh=None,
    profile_dir: str | None = None,
    max_chunks: int | None = None,
):
    """Analyze pre-tokenized ``.rawire`` file(s) (the packed ingest tier).

    The production path for repeated/at-scale analysis (SURVEY.md §8.2):
    text parse happens once in ``ruleset-analyze convert``; this run feeds
    the device from the mmap'd wire file, so the bottleneck is the device
    step, not host regex.  Registers and per-rule counts are bit-identical
    to a text run over the same logs.
    """
    if isinstance(paths, str):
        paths = [paths]
    # arm BEFORE the source: the wire reader's open/header IO is itself
    # a retry seam, and its attempts must land in THIS run's counters
    _arm_retry(cfg)
    return _run_core(
        packed,
        _WireFileSource(packed, paths),
        cfg,
        topk=topk,
        mesh=mesh,
        profile_dir=profile_dir,
        max_chunks=max_chunks,
    )


def _needed_v6_digests(tracker, dig: dict[int, int]) -> dict[int, int]:
    """digest -> address for the sources the tracker tables reference.

    The single definition of "which digests must persist/travel": the
    per-process snapshots, the elastic epoch snapshot, and the final
    distributed report gather all need exactly this set — bounded by
    the top-K capacity, not V6_DIGEST_CAP.
    """
    tag = int(pipeline.V6_ACL_TAG)
    needed = {
        int(s)
        for gid, table in tracker.tables().items()
        if int(gid) & tag
        for s in table
    }
    return {d: dig[d] for d in sorted(needed) if d in dig}


def _v6_digest_extra(source, tracker) -> dict | None:
    """Snapshot payload for the digest->address talker render map.

    The map is collected at PARSE time, so a resumed run only re-sees
    sources appearing after the crash point — pre-crash talkers would
    render as opaque ``v6#xxxx`` digests (a silent report divergence the
    chaos harness caught).
    """
    dig = getattr(source, "v6_digests", None)
    if not dig:
        return None
    rows = [[int(d), int(s)] for d, s in _needed_v6_digests(tracker, dig).items()]
    return {"v6_digests": rows} if rows else None


def _restore_v6_digests(source, snap) -> None:
    """Inverse of :func:`_v6_digest_extra` on resume (pre-PR snapshots
    carry no entry and restore nothing)."""
    dig = getattr(source, "v6_digests", None)
    if dig is None or not snap.extra:
        return
    for d, s in snap.extra.get("v6_digests", []):
        dig.setdefault(int(d), int(s))


def _stage_v6_digests(rows, dig: dict[int, int]) -> None:
    """Fold native-parser v6 rows into the capped digest->address map."""
    if not len(rows):
        return
    cap = _TextSource.V6_DIGEST_CAP
    for r in rows:
        if len(dig) >= cap:
            break
        src = pack_mod.limbs_u128(*r[pack_mod.T6_SRC:pack_mod.T6_SRC + 4])
        dig.setdefault(pack_mod.fold_src32_host(src), src)


class _FileSource:
    """Batch source over syslog file(s) via the native C++ parser."""

    def __init__(self, packed: PackedRuleset, paths: list[str]):
        from ..hostside import fastparse

        self.packer = fastparse.NativePacker(packed)
        self._paths = paths
        self._has_v6 = packed.has_v6
        self.v6_digests: dict[int, int] = {}

    def set_counts(self, parsed: int, skipped: int) -> None:
        self.packer.set_counts(parsed, skipped)

    def take_v6(self):
        """v6 rows the native parser staged (driver side channel)."""
        rows = self.packer.take_v6()
        _stage_v6_digests(rows, self.v6_digests)
        return rows

    def batches(self, skip_lines: int, batch_size: int) -> Iterator[tuple[np.ndarray, int]]:
        from ..hostside import fastparse

        return fastparse.batches_from_files(
            self._paths, self.packer, batch_size, skip_lines=skip_lines
        )


class _ShardCursorSource:
    """Sequential multi-shard source with per-shard resume cursors.

    The elastic tier's input view (runtime/elastic.py): a worker owns a
    LIST of ``(shard_index, path, start_line)`` assignments instead of one
    opaque split, consumes them in order, and tracks how many raw lines of
    each shard have been assigned to emitted batches.  Cursors snapshot at
    batch boundaries, in world-size-independent per-shard units — exactly
    what lets a re-formed cluster of ANY surviving size re-split the
    remaining work and resume with registers covering every consumed line
    exactly once.

    ``die_after_batches`` is TEST-ONLY fault injection (the elastic analog
    of ``max_chunks`` crash simulation): the process exits abruptly —
    ``os._exit``, no teardown — after that many emitted batches, exactly
    as a failing node would mid-collective.
    """

    yields_wire = False

    def __init__(
        self,
        packed: PackedRuleset,
        assignments: list[tuple[int, str, int]],
        native: bool,
        die_after_batches: int | None = None,
        pace_sec: float = 0.0,
    ):
        self._packed = packed
        self._assignments = list(assignments)
        self._native = native
        self._has_v6 = packed.has_v6
        self.v6_digests: dict[int, int] = {}
        #: shard_index -> raw lines of that shard assigned to emitted batches
        self.cursors = {int(i): int(start) for i, _p, start in self._assignments}
        self.done: set[int] = set()
        self._die_after = die_after_batches
        #: TEST-ONLY offered-load throttle (RA_ELASTIC_PACE): sleep this
        #: long per emitted batch so autoscale drills observe a stream
        #: that lasts long enough to measure and react to
        self._pace = float(pace_sec or 0.0)
        self._yielded = 0
        self._subs: list[_TextSource] = []
        if native:
            from ..hostside import fastparse

            self.packer = fastparse.NativePacker(packed)
        else:
            self.packer = LinePacker(packed)

    def set_counts(self, parsed: int, skipped: int) -> None:
        if self._native:
            self.packer.set_counts(parsed, skipped)
        else:
            self.packer.parsed, self.packer.skipped = parsed, skipped

    def take_v6(self):
        if self._native:
            rows = self.packer.take_v6()
            _stage_v6_digests(rows, self.v6_digests)
            return rows
        out: list[tuple] = []
        for sub in self._subs:
            out.extend(sub.take_v6())
        return out

    def cursor_rows(self) -> np.ndarray:
        """``[n, 4]`` uint32 (idx, cursor_lo, cursor_hi, done) rows.

        The shape the per-epoch manifest gather uses
        (parallel.distributed.allgather_rows is uint32-only; cursors split
        into 32-bit limbs so shards past 2^32 lines stay representable).
        """
        rows = [
            (idx, cur & 0xFFFFFFFF, cur >> 32, 1 if idx in self.done else 0)
            for idx, cur in sorted(self.cursors.items())
        ]
        return np.asarray(rows, dtype=np.uint32).reshape(-1, 4)

    def batches(self, skip_lines: int, batch_size: int) -> Iterator[tuple[np.ndarray, int]]:
        if skip_lines:
            from ..errors import AnalysisError

            raise AnalysisError(
                "elastic sources resume via per-shard cursors, not a "
                "global skip offset"
            )
        # deferred: elastic imports this module's driver at call time
        from .elastic import DIE_RC

        for idx, path, start in self._assignments:
            if self._native:
                from ..hostside import fastparse

                it = fastparse.batches_from_files(
                    [path], self.packer, batch_size, skip_lines=start
                )
            else:
                sub = _TextSource(self._packed, _iter_files([path]))
                sub.packer = self.packer  # shared cumulative counters
                sub.v6_digests = self.v6_digests  # shared capped digest map
                self._subs.append(sub)
                it = sub.batches(start, batch_size)
            for batch, n_raw in it:
                # cursor moves as lines are ASSIGNED to a batch, so a
                # snapshot taken after this batch steps (the driver always
                # flushes in-flight work first) covers exactly the lines
                # the cursors claim
                self.cursors[idx] += n_raw
                if self._pace:
                    time.sleep(self._pace)
                yield batch, n_raw
                self._yielded += 1
                # plan-driven twin of die_after_batches: abrupt node
                # death mid-collective (DIE_RC tells the supervisor to
                # propagate it as whole-node death)
                faults.fire("elastic.worker.die", crash_rc=DIE_RC)
                if self._die_after is not None and self._yielded >= self._die_after:
                    # crash injection: abrupt, mid-collective (the exit
                    # code is elastic.DIE_RC — the supervisor propagates
                    # it to simulate whole-node death)
                    os._exit(DIE_RC)
            self.done.add(idx)


def run_stream(
    packed: PackedRuleset,
    lines: Iterable[str],
    cfg: AnalysisConfig,
    *,
    topk: int = 10,
    mesh=None,
    profile_dir: str | None = None,
    max_chunks: int | None = None,
):
    """Run the full analysis over a stream of raw syslog lines; return Report.

    With a multi-device mesh (or by default when several devices are
    visible), the batch shards over the data axis and registers merge via
    ICI collectives; on one device this degenerates to the single-chip
    step.  Results are bit-identical either way (mergeable registers).

    With ``cfg.checkpoint_every_chunks`` set, an atomic (offset, registers)
    snapshot lands in ``cfg.checkpoint_dir`` every N chunks; with
    ``cfg.resume``, an existing snapshot is loaded and that many raw input
    lines are skipped before streaming continues — final registers are
    bit-identical to an uninterrupted run (mergeable state).

    ``max_chunks`` stops after N chunks (fault-injection in tests; also a
    cheap "analyze a prefix" knob).
    """
    _arm_retry(cfg)
    return _run_core(
        packed,
        _TextSource(packed, lines),
        cfg,
        topk=topk,
        mesh=mesh,
        profile_dir=profile_dir,
        max_chunks=max_chunks,
    )


def run_stream_file(
    packed: PackedRuleset,
    paths: str | list[str],
    cfg: AnalysisConfig,
    *,
    native: bool | None = None,
    topk: int = 10,
    mesh=None,
    profile_dir: str | None = None,
    max_chunks: int | None = None,
    feed_workers: int = 0,
    feed_mode: str = "process",
):
    """Analyze syslog file(s), using the native C++ parser when available.

    ``native=None`` auto-selects: the C++ fast path if its library loads
    (building it on first use), else the pure-Python line path.  Results
    are identical either way; only host-side parse throughput differs.

    With ``cfg.prefetch_depth > 0`` (the default) the parse runs on a
    background producer that keeps a bounded queue of packed,
    device-ready batches ahead of the device step (runtime/ingest.py) —
    host parse, H2D transfer, and device compute overlap instead of
    serializing, with the report bit-identical to the synchronous
    driver.

    ``feed_workers > 1`` parses with that many workers over file shards
    — worker PROCESSES packing into shared memory (``feed_mode=
    "process"``, hostside.feeder.ParallelFeeder) or in-process worker
    THREADS around the GIL-releasing native parser (``feed_mode=
    "thread"``, hostside.feeder.ThreadedFeeder) — the multi-core
    input-split tier.  Chunk boundaries then follow raw-line counts only
    (a dual-evaluation line never closes a batch early; the grouped
    batch is 2x wide instead), so per-chunk candidates may differ from
    the sequential path.  Registers, per-rule counts, and the unused set
    are identical either way (order-invariant mergeable state); the
    top-K talker section is the one approximation whose candidate pool
    is chunk-boundary-sensitive, so borderline talkers can differ
    between feeder and sequential runs.
    """
    from ..hostside import fastparse

    _arm_retry(cfg)
    if isinstance(paths, str):
        paths = [paths]
    use_native = native if native is not None else fastparse.available()
    if feed_mode not in ("process", "thread", "ring"):
        from ..errors import AnalysisError

        raise AnalysisError(
            f"feed_mode must be 'process', 'thread' or 'ring', got {feed_mode!r}"
        )
    if feed_mode == "ring" and not (feed_workers and feed_workers >= 1):
        from ..errors import AnalysisError

        # an explicitly requested topology must never be silently dropped
        raise AnalysisError(
            "feed_mode='ring' needs feed_workers >= 1 (the per-chip "
            "producer pool size)"
        )
    if feed_workers and (feed_workers > 1 or feed_mode == "ring"):
        if native is False:
            from ..errors import AnalysisError

            raise AnalysisError(
                "feed_workers requires the native parser; drop native=False"
            )
        from ..hostside.feeder import ParallelFeeder, RingFeeder, ThreadedFeeder

        feeder_cls = {
            "thread": ThreadedFeeder,
            "process": ParallelFeeder,
            "ring": RingFeeder,
        }[feed_mode]
        source = feeder_cls(
            packed, paths, n_workers=feed_workers,
            stall_timeout=cfg.stall_timeout_sec,
        )
    elif use_native:
        source = _FileSource(packed, paths)
    else:
        source = _TextSource(packed, _iter_files(paths))
    return _run_core(
        packed,
        source,
        cfg,
        topk=topk,
        mesh=mesh,
        profile_dir=profile_dir,
        max_chunks=max_chunks,
    )


def run_stream_file_distributed(
    packed: PackedRuleset,
    local_paths: str | list[str],
    cfg: AnalysisConfig,
    *,
    native: bool | None = None,
    topk: int = 10,
    return_state: bool = False,
    max_chunks: int | None = None,
    elastic=None,
):
    """Multi-process analysis: each process feeds ITS OWN input split.

    The reborn Hadoop job (SURVEY.md §3c): ``jax.distributed`` must already
    be initialized (parallel.distributed.init_distributed); the mesh spans
    every device of every process, each process parses only its own files
    (the input-split analog), and the per-chunk global batch is assembled
    with ``jax.make_array_from_process_local_data``.  The SAME shard_map
    step then merges registers with psum/pmax — over ICI within a host,
    DCN between hosts.  Every process returns the identical Report.

    Checkpointing: every process snapshots under its own
    ``checkpoint_dir/proc-<i>-of-<n>`` subdirectory — registers are
    replicated (identical everywhere) but each process must remember its
    OWN offset into its OWN split.  The chunk loop is collective, so all
    processes snapshot at the same chunk count; resume verifies that in
    lockstep and refuses a changed process count.

    ``elastic`` (a ``runtime.elastic.ElasticRunSpec``) switches the run
    into the supervised elastic tier: the source becomes a per-shard
    cursor source over the spec's assignments (``local_paths`` is
    ignored), per-process snapshots are replaced by ONE epoch-tagged,
    world-size-independent checkpoint in ``spec.epoch_dir`` (registers +
    merged cursor manifest, written by the generation's rank 0), and the
    fingerprint deliberately excludes mesh width and process layout so a
    re-formed cluster of any surviving size can resume it.  Driven by
    ``runtime.elastic.ElasticSupervisor``, never called this way directly
    by operators.
    """
    from ..hostside import fastparse
    from ..parallel import distributed as dist
    from ..parallel import mesh as mesh_lib
    from ..parallel.step import make_parallel_step, make_parallel_step_stacked
    from jax.sharding import PartitionSpec as P

    from ..errors import AnalysisError

    stacked = cfg.layout == "stacked"
    if cfg.coalesce != "off":
        # per-process unique-row counts diverge, and to_global assembles
        # ONE global array per round — every process would need the same
        # post-compaction shape.  Weighted .rawire inputs (converted with
        # `convert --coalesce`) are the distributed way to the same win.
        raise AnalysisError(
            "coalesce applies to the single-process stream drivers only; "
            "for distributed runs convert the input with "
            "`ruleset-analyze convert --coalesce` instead"
        )
    if isinstance(local_paths, str):
        local_paths = [local_paths]
    from ..hostside.wire import is_wire_file

    _arm_retry(cfg)  # before the source: wire open IO is a retry seam
    n_wire = sum(1 for p in local_paths if is_wire_file(p))
    if n_wire and n_wire < len(local_paths):
        raise AnalysisError(
            "cannot mix .rawire and text inputs in one --logs list"
        )
    if elastic is not None:
        if native is None:
            native = fastparse.available()
        source = _ShardCursorSource(
            packed,
            elastic.assignments,
            native,
            die_after_batches=elastic.die_after_batches,
            pace_sec=getattr(elastic, "pace_sec", 0.0),
        )
    elif n_wire:
        source = _WireFileSource(packed, local_paths)
    else:
        if native is None:
            native = fastparse.available()
        source = _FileSource(packed, local_paths) if native else _TextSource(
            packed, _iter_files(local_paths)
        )
    # Pipelined ingest, collective edition: the producer thread overlaps
    # THIS process's parse (and, flat text path, the wire bit-pack) with
    # the collective step rounds.  device_put stays on the consumer side
    # here — to_global assembles a multi-process global array and is not
    # produced ahead.  Counters / v6 rows / elastic cursors commit only
    # as batches are consumed, so epoch snapshots record the last
    # COMMITTED batch, never one the producer merely prefetched.
    armed_here = faults.arm_spec(cfg.fault_plan)
    prepacked = False
    if cfg.prefetch_depth > 0:
        from .ingest import PrefetchingSource

        _pack = None
        if not stacked and not n_wire:
            _pack = pack_mod.compact_batch
            prepacked = True
        source = PrefetchingSource(
            source, cfg.prefetch_depth, pack=_pack,
            stall_timeout=cfg.stall_timeout_sec,
        )
    try:
        wire_src = getattr(source, "yields_wire", False)
        wire_weighted = getattr(source, "yields_wire_weighted", False)
        if wire_weighted:
            _check_weighted_input_config(cfg)

        mesh = dist.make_global_mesh(
            cfg.mesh_axis, topology=cfg.mesh_shape, dcn=cfg.mesh_dcn
        )
        # batch axes of the mesh: the flat data axis, or the ("dcn",
        # data) pair of the hybrid topology — one value for every
        # PartitionSpec below
        data_ax = mesh_lib.data_axes(mesh, cfg.mesh_axis)
        pid, nproc = jax.process_index(), jax.process_count()
        global_batch = mesh_lib.pad_batch_size(
            max(cfg.batch_size, 2 if packed.bindings_out else 1) * nproc,
            mesh, cfg.mesh_axis,
        )
        local_batch = global_batch // nproc

        if stacked:
            from ..hostside.pack import GroupBuffer, stack_rules

            # per-GLOBAL-batch lane, sharded over every device; each process
            # contributes its local lane slice from its own group buffer
            lane = cfg.stacked_lane or max(1, cfg.batch_size // max(1, packed.n_acls))
            lane = mesh_lib.pad_batch_size(lane * nproc, mesh, cfg.mesh_axis)
            local_lane = lane // nproc
            rules = pipeline.DeviceRulesetStacked(
                rules3d=dist.to_global(mesh, stack_rules(packed), P()),
                deny_key=dist.to_global(
                    mesh, packed.deny_key.astype(np.uint32), P()
                ),
            )
            step = make_parallel_step_stacked(mesh, cfg, packed.n_keys)
            gbuf = GroupBuffer(max(packed.n_acls, 1), local_lane)
        else:
            rules_host = pipeline.ship_ruleset_host(packed)
            rules = pipeline.DeviceRuleset(
                rules=dist.to_global(mesh, rules_host.rules, P()),
                deny_key=dist.to_global(mesh, rules_host.deny_key, P()),
                rules_fm=None,
            )
            step = make_parallel_step(mesh, cfg, packed.n_keys)
            gbuf = None
        # IPv6 side path (collective twin of _run_core's): v6 rows stage
        # per process at a data-dependent rate, so full chunks drain
        # through the same lockstep ready-round protocol as the stacked
        # layout — every process steps the v6 program the same number of
        # times, padding with all-invalid batches when its queue is dry.
        step6 = None
        rules6_g = None
        if packed.has_v6 and (
            hasattr(source, "take_v6") or hasattr(source, "batches6")
        ):
            from ..parallel.step import make_parallel_step6

            r6h = pipeline.ship_ruleset6_host(packed)
            rules6_g = pipeline.DeviceRuleset6(
                rules6=dist.to_global(mesh, r6h.rules6, P()),
                deny_key=dist.to_global(mesh, r6h.deny_key, P()),
            )
            step6 = make_parallel_step6(mesh, cfg, packed.n_keys)
        ready6: deque[np.ndarray] = deque()  # full [TUPLE6_COLS, local_batch]
        buf6 = None
        fill6 = 0
        packer = source.packer
        pending: deque[pipeline.ChunkOut] = deque()

        # one-time jit/compile cost of each device program, priced apart
        # from the sustained rate (shared discipline: metrics.DispatchTimer)
        from .metrics import DispatchTimer

        _dispatch = DispatchTimer()
        _first_dispatch = _dispatch.first

        from . import checkpoint as ckpt

        # per-process snapshot dir: registers are identical everywhere, but
        # the offset is into THIS process's own input split
        my_ckpt_dir = os.path.join(cfg.checkpoint_dir, f"proc-{pid}-of-{nproc}")
        if elastic is not None:
            # Elastic epoch checkpoints are WORLD-SIZE-INDEPENDENT: the
            # fingerprint pins ruleset + sketch geometry + layout but NOT
            # mesh width or process layout, because re-formation resumes
            # on a smaller world by design.  (Candidate-table chunk
            # boundaries shift across world sizes; the order-invariant
            # registers — exact counts, CMS, HLL — and therefore the
            # unused-rule report cannot.)
            fp = ckpt.fingerprint(packed, cfg, 1, 0) + "-elastic"
        else:
            fp = (
                ckpt.fingerprint(
                    packed, cfg, mesh_lib.data_extent(mesh), local_lane if stacked else 0
                )
                + f"-dist{pid}of{nproc}"
                + (("-wirew" if wire_weighted else "-wire") if wire_src else "")
            )
        lines_consumed = 0
        n_chunks = 0
        snap = None
        if elastic is not None:
            snap = elastic.snapshot
            if snap is not None and snap.fingerprint != fp:
                raise ckpt.CheckpointMismatch(
                    f"elastic epoch snapshot in {elastic.epoch_dir!r} was "
                    "taken with a different ruleset, sketch geometry, or "
                    "layout; refusing to merge"
                )
            # every process read the same epoch file; one tiny allgather
            # catches a stale-storage torn view before any work happens
            chunks_all = dist.value_across_processes(
                snap.n_chunks if snap is not None else -1
            )
            if not (chunks_all == chunks_all[0]).all():
                raise ckpt.CheckpointMismatch(
                    "processes loaded different elastic epoch snapshots "
                    f"({chunks_all.tolist()}); shared storage is inconsistent"
                )
        elif cfg.resume:
            # Every process must reach every allgather: evaluate ALL local
            # conditions first, gather once, and raise the SAME verdict
            # everywhere — a lone early raise would leave the other processes
            # blocked in the next collective instead of surfacing the error.
            layout_err = _dist_ckpt_layout_error(cfg.checkpoint_dir, nproc)
            corrupt_err = None
            if layout_err is None:
                try:
                    snap = ckpt.load(my_ckpt_dir)
                except (ckpt.CheckpointCorrupt, OSError) as e:
                    # a LOCAL raise here would strand the other processes
                    # in the allgather below — classify and gather instead.
                    # OSError too: an unreadable pointer (PermissionError,
                    # IsADirectoryError) is as stranding as a corrupt one.
                    corrupt_err = e
            local_state = 0  # 0 = no snapshot
            if layout_err is not None:
                local_state = 3  # foreign process layout
            elif corrupt_err is not None:
                local_state = 4  # undecodable snapshot on this process
            elif snap is not None:
                local_state = 1 if snap.fingerprint == fp else 2
            states = dist.value_across_processes(local_state)
            chunks_all = dist.value_across_processes(
                snap.n_chunks if snap is not None else -1
            )
            if (states == 4).any():
                raise ckpt.CheckpointCorrupt(
                    str(corrupt_err)
                    if corrupt_err is not None
                    else f"another process found an undecodable snapshot in "
                    f"{cfg.checkpoint_dir!r}"
                )
            if (states == 3).any():
                raise ckpt.CheckpointMismatch(
                    layout_err
                    or f"another process found a foreign process layout in "
                    f"{cfg.checkpoint_dir!r}"
                )
            if (states == 2).any():
                raise ckpt.CheckpointMismatch(
                    f"snapshot under {cfg.checkpoint_dir!r} was taken with a "
                    "different ruleset, geometry, or process layout; refusing "
                    "to merge"
                )
            n_have = int((states == 1).sum())
            if 0 < n_have < nproc:
                raise ckpt.CheckpointMismatch(
                    f"only {n_have}/{nproc} processes found a snapshot in "
                    f"{cfg.checkpoint_dir!r}; all or none must resume"
                )
            if n_have and not (chunks_all == chunks_all[0]).all():
                raise ckpt.CheckpointMismatch(
                    "processes hold snapshots from different chunk counts "
                    f"({chunks_all.tolist()}); the checkpoint is inconsistent"
                )
        if snap is not None:
            state = ckpt.state_of(snap, lambda v: dist.to_global(mesh, v, P()))
            tracker = ckpt.restore_tracker(snap, cfg.sketch.topk_capacity)
            if elastic is not None:
                # the epoch snapshot stores GLOBAL cumulative counters;
                # seed them on rank 0 only — the final totals re-aggregate
                # with sum_across_processes, so base + every rank's new
                # contributions add exactly once
                if pid == 0:
                    source.set_counts(snap.parsed, snap.skipped)
                    lines_consumed = snap.lines_consumed
            else:
                source.set_counts(snap.parsed, snap.skipped)
                lines_consumed = snap.lines_consumed
            # every rank re-seeds the talker render map (merged at save
            # for elastic, per-split otherwise): pre-crash talkers must
            # not render as opaque digests after a resume
            _restore_v6_digests(source, snap)
            n_chunks = snap.n_chunks
        else:
            state_host = pipeline.init_state_host(packed.n_keys, cfg)
            state = pipeline.AnalysisState(
                **{
                    k: dist.to_global(mesh, getattr(state_host, k), P())
                    for k in pipeline.AnalysisState._fields
                }
            )
            tracker = TopKTracker(cfg.sketch.topk_capacity)
        lines_at_start = lines_consumed  # throughput covers this run only

        def drain(out: pipeline.ChunkOut) -> None:
            tracker.offer_chunk(
                np.asarray(out.cand_acl), np.asarray(out.cand_src), np.asarray(out.cand_est)
            )

        def collective_flush() -> None:
            # Snapshot barrier for the stacked layout (VERDICT r3 #4): flush
            # emissions are data-dependent per process, so every process
            # drains its group buffer through the SAME lockstep ready-queue
            # protocol the end-of-stream path uses — processes whose queue ran
            # dry keep stepping padded batches until everyone is empty, so all
            # processes reach the snapshot at the same chunk count with no
            # lines in limbo.
            ready.extend(gbuf.flush())
            while True:
                has = bool(ready)
                if not dist.all_processes_have_data(has):
                    break
                step_grouped_round(has)

        def pull_v6() -> None:
            # stage source-parsed v6 rows; enqueue each full local chunk
            # (text sources; wire v6 rows arrive via the phase-2 loop)
            nonlocal buf6, fill6
            if not hasattr(source, "take_v6"):
                return
            rows = source.take_v6()
            i = 0
            while i < len(rows):
                if buf6 is None:
                    buf6 = np.zeros(
                        (pack_mod.TUPLE6_COLS, local_batch), dtype=np.uint32
                    )
                take = min(local_batch - fill6, len(rows) - i)
                buf6[:, fill6:fill6 + take] = np.asarray(
                    rows[i:i + take], dtype=np.uint32
                ).T
                fill6 += take
                i += take
                if fill6 == local_batch:
                    ready6.append(buf6)
                    buf6 = None
                    fill6 = 0

        def step_v6_round(has: bool) -> None:
            nonlocal state, n_chunks
            b = (
                ready6.popleft()
                if has
                else np.zeros(
                    (pack_mod.TUPLE6_COLS, local_batch), dtype=np.uint32
                )
            )
            gb = dist.to_global(mesh, b, P(None, data_ax))
            state, out = _first_dispatch("v6", step6, state, rules6_g, gb, n_chunks)
            pending.append(out)
            if len(pending) > 2:
                drain(pending.popleft())
            n_chunks += 1

        def drain_v6_rounds() -> None:
            # step full v6 chunks in lockstep; one tiny allgather per round
            # plus a terminating one (skipped entirely for pure-v4 rulesets)
            if step6 is None:
                return
            while True:
                has = bool(ready6)
                if not dist.all_processes_have_data(has):
                    break
                step_v6_round(has)

        def collective_flush_v6() -> None:
            # snapshot/EOF barrier: drain EVERYTHING including the partial
            # chunk, so no consumed line is in limbo across a snapshot
            nonlocal buf6, fill6
            if step6 is None:
                return
            pull_v6()
            if fill6:
                ready6.append(buf6)  # padding columns carry valid=0
                buf6 = None
                fill6 = 0
            drain_v6_rounds()

        def save_epoch_snapshot() -> None:
            # Elastic epoch checkpoint: replicated registers + the merged
            # world-size-independent cursor manifest.  EVERY rank takes
            # part in the gathers (they are collective); only the
            # generation's rank 0 writes, atomically, so survivors of a
            # later failure all load one consistent epoch.
            merged = dist.allgather_rows(source.cursor_rows())
            cursors = dict(elastic.base_cursors)
            done = set(elastic.base_done)
            for r in merged:
                cursors[int(r[0])] = int(r[1]) | (int(r[2]) << 32)
                if int(r[3]):
                    done.add(int(r[0]))
            agg = dist.sum_across_processes(
                {
                    "lines": lines_consumed,
                    "parsed": packer.parsed,
                    "skipped": packer.skipped,
                }
            )
            # each rank only holds digests for ITS split's sources; the
            # epoch snapshot needs the union so ANY surviving world can
            # render every persisted talker candidate (collective: every
            # rank gathers, rank 0 writes)
            dig = getattr(source, "v6_digests", None) or {}
            drows = np.array(
                [
                    (d, *pack_mod.u128_limbs(s))
                    for d, s in _needed_v6_digests(tracker, dig).items()
                ],
                dtype=np.uint32,
            ).reshape(-1, 5)
            dmerged = dist.allgather_rows(drows)
            if pid != 0:
                return
            v6_digest_rows = [
                [int(r[0]), int(pack_mod.limbs_u128(*r[1:5]))] for r in dmerged
            ]
            ckpt.save(
                elastic.epoch_dir,
                ckpt.snapshot_of(
                    state,
                    lines_consumed=agg["lines"],
                    n_chunks=n_chunks,
                    parsed=agg["parsed"],
                    skipped=agg["skipped"],
                    tracker=tracker,
                    fingerprint=fp,
                    extra={
                        **(
                            {"v6_digests": v6_digest_rows}
                            if v6_digest_rows
                            else {}
                        ),
                        "elastic": {
                            "epoch": elastic.epoch,
                            "world": nproc,
                            "shards": list(elastic.shards),
                            "cursors": {
                                str(k): v for k, v in sorted(cursors.items())
                            },
                            "done": sorted(done),
                        }
                    },
                ),
            )

        def save_snapshot() -> None:
            if stacked:
                collective_flush()
            collective_flush_v6()
            while pending:
                drain(pending.popleft())
            pipeline.sync_state(state)
            if elastic is not None:
                save_epoch_snapshot()
                return
            ckpt.save(
                my_ckpt_dir,
                ckpt.snapshot_of(
                    state,
                    lines_consumed=lines_consumed,
                    n_chunks=n_chunks,
                    parsed=packer.parsed,
                    skipped=packer.skipped,
                    tracker=tracker,
                    fingerprint=fp,
                    extra=_v6_digest_extra(source, tracker),
                ),
            )

        from .metrics import ThroughputMeter

        meter = ThroughputMeter(cfg.report_every_chunks)
        # elastic sources resume via their per-shard cursors; the global
        # offset (rank 0's cumulative base) must not be re-skipped
        it = source.batches(
            0 if elastic is not None else lines_consumed, local_batch
        )
        if stacked:
            empty = None
        elif prepacked:
            # padding rounds must match the producer's output layout
            empty = pack_mod.compact_batch(
                np.zeros((TUPLE_COLS, local_batch), dtype=np.uint32)
            )
        else:
            if wire_src:
                empty_cols = (
                    pack_mod.WIREW_COLS if wire_weighted else pack_mod.WIRE_COLS
                )
            else:
                empty_cols = TUPLE_COLS
            empty = np.zeros((empty_cols, local_batch), dtype=np.uint32)
        last_snap_chunks = n_chunks
        chunks_this_run = 0
        aborted = False
        # Stacked: grouped batches emit from the group buffer at a
        # data-dependent cadence, so a ready-queue decouples source pulls from
        # the collective loop — each round steps at most ONE grouped batch per
        # process, and processes whose queue ran dry pad with an all-invalid
        # batch until every queue is empty.
        ready: deque[np.ndarray] = deque()
        src_done = False

        def refill_ready() -> None:
            nonlocal src_done, lines_consumed
            while not ready and not src_done:
                nxt = next(it, None)
                if nxt is None:
                    src_done = True
                    ready.extend(gbuf.flush())
                    return
                batch_np, n_raw = nxt
                lines_consumed += n_raw
                meter.tick(n_raw)
                if batch_np is None:  # zero-valid text batch: lines only
                    continue
                cols = pack_mod.expand_batch(batch_np) if wire_src else batch_np
                ready.extend(gbuf.add(np.ascontiguousarray(cols.T)))

        def next_real():
            # pull the next steppable batch, absorbing zero-valid (None)
            # text batches as pure raw-line accounting — the collective
            # round protocol only ever sees batches that need a step
            nonlocal lines_consumed
            while True:
                nxt = next(it, None)
                if nxt is None or nxt[0] is not None:
                    return nxt
                lines_consumed += nxt[1]
                meter.tick(nxt[1])
                if step6 is not None:
                    pull_v6()

        def step_grouped_round(has: bool) -> None:
            nonlocal state, n_chunks
            grouped = (
                ready.popleft()
                if has
                else np.zeros(
                    (max(packed.n_acls, 1), TUPLE_COLS, local_lane), dtype=np.uint32
                )
            )
            with obs.span("ingest.pack"):
                # a weighted wire input's rows carry weights in T_VALID
                # (expand_batch); the 1-bit compactor would crush them
                wire = (
                    pack_mod.compact_grouped_w(grouped)
                    if wire_weighted
                    else pack_mod.compact_grouped(grouped)
                )
                gbatch = dist.to_global(mesh, wire, P(None, None, data_ax))
            state, out = _first_dispatch("v4", step, state, rules, gbatch, n_chunks)
            pending.append(out)
            if len(pending) > 2:
                drain(pending.popleft())
            n_chunks += 1

        while True:
            if stacked:
                refill_ready()
                has = bool(ready)
            else:
                nxt = next_real()
                has = nxt is not None
            # collective agreement: everyone steps while anyone has data
            if not dist.all_processes_have_data(has):
                break
            if stacked:
                step_grouped_round(has)
            else:
                batch_np, n_raw = nxt if has else (empty, 0)
                lines_consumed += n_raw
                meter.tick(n_raw)
                with obs.span("ingest.pack"):
                    wire = (
                        batch_np
                        if wire_src or prepacked
                        else pack_mod.compact_batch(batch_np)
                    )
                    gbatch = dist.to_global(mesh, wire, P(None, data_ax))
                state, out = _first_dispatch("v4", step, state, rules, gbatch, n_chunks)
                pending.append(out)
                if len(pending) > 2:
                    drain(pending.popleft())
                n_chunks += 1
            if step6 is not None:
                pull_v6()
                drain_v6_rounds()
            chunks_this_run += 1
            # the loop is collective, so every process reaches the cadence at
            # the same n_chunks and snapshots the same register state
            if (
                cfg.checkpoint_every_chunks
                and n_chunks - last_snap_chunks >= cfg.checkpoint_every_chunks
            ):
                save_snapshot()
                last_snap_chunks = n_chunks
            if max_chunks is not None and chunks_this_run >= max_chunks:
                aborted = True  # crash simulation: skip the final snapshot
                break

        if stacked and aborted:
            # drain buffered lines after a max_chunks abort: they are already
            # counted in lines_consumed / the packer counters, and a report
            # claiming lines the registers never saw would be a lie (the same
            # invariant _run_core's post-abort gbuf flush preserves).  The
            # drain stays collective: everyone keeps stepping until every
            # process's queue is dry.
            src_done = True
            ready.extend(gbuf.flush())
            while True:
                has = bool(ready)
                if not dist.all_processes_have_data(has):
                    break
                step_grouped_round(has)
        # Phase 2 — wire-v2 v6 sections, in collective rounds: every
        # process steps while ANY still has v6 rows, padding when dry,
        # so the jitted v6 program's collectives stay aligned.
        b6fn = getattr(source, "batches6", None)
        if b6fn is not None and step6 is not None and not aborted:
            it6 = b6fn(max(0, lines_at_start - source.n4_rows), local_batch)
            while True:
                nxt6 = next(it6, None)
                has6 = nxt6 is not None
                if not dist.all_processes_have_data(has6):
                    break
                if has6:
                    b6, n_rows6 = nxt6
                    lines_consumed += n_rows6
                    meter.tick(n_rows6)
                else:
                    b6 = np.zeros(
                        (
                            pack_mod.WIRE6W_COLS
                            if wire_weighted
                            else pack_mod.WIRE6_COLS,
                            local_batch,
                        ),
                        dtype=np.uint32,
                    )
                gb6 = dist.to_global(mesh, b6, P(None, data_ax))
                state, out = _first_dispatch("v6", step6, state, rules6_g, gb6, n_chunks)
                pending.append(out)
                if len(pending) > 2:
                    drain(pending.popleft())
                n_chunks += 1
                chunks_this_run += 1
                if (
                    cfg.checkpoint_every_chunks
                    and n_chunks - last_snap_chunks >= cfg.checkpoint_every_chunks
                ):
                    save_snapshot()
                    last_snap_chunks = n_chunks
                if max_chunks is not None and chunks_this_run >= max_chunks:
                    aborted = True
                    break

        # v6 rows from consumed lines drain collectively on BOTH the
        # normal and aborted exits (same invariant as the stacked drain)
        collective_flush_v6()

        pipeline.sync_state(state)
        elapsed = meter.elapsed()  # before the final snapshot write (as _run_core)
        if cfg.checkpoint_every_chunks and not aborted:
            save_snapshot()
        while pending:
            drain(pending.popleft())
        local_total, local_skipped = lines_consumed, packer.skipped
        if wire_src and not aborted:
            # restore the converter's raw-line accounting for this process's
            # fully-consumed wire split (rows != raw text lines)
            p = source.totals_patch(True)
            local_total, local_skipped = p["lines_total"], p["lines_skipped"]
        agg = dist.sum_across_processes(
            {
                "lines_total": local_total,
                "lines_matched": packer.parsed,
                "lines_skipped": local_skipped,
                # throughput covers THIS run's lines only (totals above are
                # cumulative across resumes)
                "lines_this_run": lines_consumed - lines_at_start,
            }
        )
        lines_this_run = agg.pop("lines_this_run")
        compile_sec = _dispatch.compile_sec()
        sustained = elapsed - compile_sec
        totals = {
            **agg,
            "chunks": n_chunks,
            "processes": nproc,
            "elapsed_sec": round(elapsed, 4),
            "lines_per_sec": round(lines_this_run / elapsed, 1) if elapsed > 0 else 0.0,
            # one-time jit/XLA-compile cost (this process's first dispatch
            # of each program), separated from the sustained rate
            "compile_sec": round(compile_sec, 4),
            "sustained_lines_per_sec": (
                round(lines_this_run / sustained, 1) if sustained > 0 else 0.0
            ),
            # the meter's own cumulative numbers (THIS process's split),
            # folded in so artifacts stop re-deriving them from stderr
            "throughput": meter.summary(),
        }
        stats_fn = getattr(source, "ingest_stats", None)
        if stats_fn is not None:
            totals["ingest"] = stats_fn()
        lat_fn = getattr(source, "latency_summary", None)
        if lat_fn is not None:
            lat = lat_fn()
            if lat:
                # produce->commit batch-latency percentiles (DESIGN §20)
                totals["latency"] = lat
        if elastic is not None:
            # which generation of the elastic cluster produced the report
            totals["elastic_epoch"] = elastic.epoch
        v6_digests = getattr(source, "v6_digests", None)
        if step6 is not None:
            # The tracker is replicated but each process's digest map only
            # covers ITS split's sources; gather just the rows the final
            # candidates need (tiny) so every process renders the SAME
            # report — the driver's identical-everywhere contract.
            tag = int(pipeline.V6_ACL_TAG)
            needed = {
                int(s)
                for gid, table in tracker.tables().items()
                if int(gid) & tag
                for s in table
            }
            local = v6_digests or {}
            rows = np.array(
                [
                    (d, *pack_mod.u128_limbs(local[d]))
                    for d in sorted(needed)
                    if d in local
                ],
                dtype=np.uint32,
            ).reshape(-1, 5)
            merged = dist.allgather_rows(rows)
            v6_digests = {
                int(r[0]): pack_mod.limbs_u128(*r[1:5]) for r in merged
            }
        report = pipeline.finalize(
            state, packed, cfg, tracker, topk=topk, totals=totals,
            v6_digests=v6_digests,
        )
        if return_state:
            return report, pipeline.state_to_host(state)
        return report
    finally:
        # release the wire mmaps deterministically (ADVICE r4): a
        # long-lived driver iterating many wire inputs must not wait
        # for GC to drop file mappings
        close = getattr(source, "close", None)
        if close is not None:
            close()
        if armed_here:
            # a plan this run armed must not leak (env export included)
            # into a later run in the same process
            faults.disarm()


def _check_weighted_input_config(cfg: AnalysisConfig) -> None:
    """Refuse device formulations that are not weight-linear/exact.

    A weighted (RAWIREv3) input reaches the step with weights the config
    validator never saw, so every entry of the ONE declarative
    compatibility table (``config.WEIGHTED_INPUT_REFUSALS`` — shared
    with the config-time ``coalesce`` checks and the static linter,
    which *derives* the same set from the traced jaxprs) is also
    refused here, unconditionally: wire weights are unbounded by the
    stored batch size, so the table's config-time batch bounds do not
    apply.

    ``update_impl='sorted'`` needs NO entry there: every sorted segment
    reduce is weight-linear (sums of the uint32 weight plane) or
    idempotent by construction (DESIGN §15), so weighted inputs are
    accepted everywhere the default scatter path accepts them —
    tests/test_sorted_update.py pins the combination, and the linter
    proves it (tests/test_ralint.py).
    """
    from ..config import WEIGHTED_INPUT_REFUSALS
    from ..errors import AnalysisError

    for r in WEIGHTED_INPUT_REFUSALS:
        if getattr(cfg, r.field) == r.value:
            raise AnalysisError(
                "weighted (coalesced) wire inputs are incompatible with "
                f"{r.field}={r.value!r}: {r.reason}"
            )


def _iter_files(paths: list[str]):
    for path in paths:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            yield from f


def _dist_ckpt_layout_error(ckpt_dir: str, nproc: int) -> str | None:
    """Error message if resuming this layout would silently restart.

    Snapshot subdirs are named ``proc-<i>-of-<n>``.  Foreign-``n`` dirs
    are only fatal when NO matching-``n`` dirs exist: then a resume would
    find nothing and silently start from scratch even though an (older,
    differently-laid-out) checkpoint is clearly present.  When a complete
    current-layout set coexists with stale dirs, the stale ones are
    ignored.
    """
    import re

    try:
        entries = os.listdir(ckpt_dir)
    except OSError:
        return None
    foreign = set()
    have_matching = False
    for e in entries:
        m = re.fullmatch(r"proc-\d+-of-(\d+)", e)
        if not m:
            continue
        if int(m.group(1)) == nproc:
            have_matching = True
        else:
            foreign.add(int(m.group(1)))
    if foreign and not have_matching:
        return (
            f"{ckpt_dir!r} holds snapshots from a "
            f"{sorted(foreign)[0]}-process run; this job has {nproc} "
            "processes"
        )
    return None


def _run_core(
    packed: PackedRuleset,
    source,
    cfg: AnalysisConfig,
    *,
    topk: int,
    mesh,
    profile_dir: str | None,
    max_chunks: int | None,
):
    """Run the chunk loop, deterministically closing the source after.

    Sources holding OS resources (the wire reader's mmaps, the ingest
    pipeline's producer threads) expose ``close()``; releasing them here
    instead of at GC time keeps repeated wire runs in one process from
    accumulating file mappings (ADVICE r4) and never strands a prefetch
    producer on a full queue.

    Pipelined ingest (``cfg.prefetch_depth > 0``, runtime/ingest.py)
    wraps the source HERE, so the ``finally`` below closes the wrapper:
    a background producer runs the source iterator (parse / feeder /
    mmap reads) and — for flat layouts — also bit-packs and issues the
    async sharded ``device_put``, so the queue holds device-ready
    batches and H2D of chunk N+k overlaps the step of chunk N.  Reports
    are bit-identical to the synchronous path (batches commit in source
    order).
    """
    from ..parallel import mesh as mesh_lib
    from . import coalesce as coalesce_mod

    armed_here = faults.arm_spec(cfg.fault_plan)
    coal = None
    try:
        if mesh is None:
            mesh = mesh_lib.make_mesh(
                axis=cfg.mesh_axis,
                topology=cfg.mesh_shape,
                dcn=cfg.mesh_dcn,
            )
        # Flow coalescing (ISSUE 5): compact duplicate evaluation tuples
        # into (unique row, weight) pairs before the device step.  The
        # compactor runs inside the pack stage, so under pipelined ingest
        # the O(B) host hash pass runs on the producer thread and
        # overlaps device compute exactly like the wire bit-pack does.
        coal = coalesce_mod.make_coalescer(
            cfg,
            mesh_lib.pad_batch_size(cfg.batch_size, mesh, cfg.mesh_axis),
            mesh_lib.data_extent(mesh),
        )
        if coal is not None:
            obs.register_sampler("coalesce", coal.sample_metrics)
        # per-chip ring feeder (ISSUE 11): resolve the ring count to the
        # mesh's data extent, and pick the consumption mode — per-chip
        # views for the direct device_put path (flat + prefetch), or
        # assembled plain batches everywhere else (sync, stacked)
        ring_src = getattr(source, "yields_ring", False)
        if ring_src:
            if coal is not None:
                from ..errors import AnalysisError

                raise AnalysisError(
                    "runtime coalescing is not available with the ring "
                    "feeder (per-chip shards compact independently, which "
                    "would change batch grouping); pre-coalesce with "
                    "`convert --coalesce` or the convert fleet instead"
                )
            if not getattr(source, "n_rings", None):
                source.n_rings = mesh_lib.data_extent(mesh)
            source.emit_views = (
                cfg.prefetch_depth > 0 and cfg.layout != "stacked"
            )
        device_ready = False
        if cfg.prefetch_depth > 0:
            from ..hostside import pack as _pm
            from .ingest import PrefetchingSource

            pack = None
            if cfg.layout != "stacked":
                axis = cfg.mesh_axis
                wire_src = getattr(source, "yields_wire", False)
                if ring_src:
                    # per-chip compact + device_put straight from each
                    # chip's ring view; no global host-side assembly
                    def pack(rb):
                        return mesh_lib.shard_ring_batch(mesh, rb, axis)
                elif wire_src:
                    def pack(b):
                        if coal is not None and coal.enabled():
                            b = coal.wire4(b)
                        return mesh_lib.shard_batch(mesh, b, axis)
                else:
                    def pack(b):
                        if coal is not None and coal.enabled():
                            wire = _pm.compact_batch_w(coal.tuple4(b))
                        else:
                            wire = _pm.compact_batch(b)
                        return mesh_lib.shard_batch(mesh, wire, axis)
                device_ready = True
            source = PrefetchingSource(
                source, cfg.prefetch_depth, pack=pack,
                stall_timeout=cfg.stall_timeout_sec,
            )
        return _run_core_impl(
            packed,
            source,
            cfg,
            topk=topk,
            mesh=mesh,
            profile_dir=profile_dir,
            max_chunks=max_chunks,
            device_ready=device_ready,
            coal=coal,
        )
    finally:
        if coal is not None:
            obs.unregister_sampler("coalesce")
        close = getattr(source, "close", None)
        if close is not None:
            close()
        if armed_here:
            # a plan this run armed must not leak (env export included)
            # into a later run in the same process
            faults.disarm()


def _run_core_impl(
    packed: PackedRuleset,
    source,
    cfg: AnalysisConfig,
    *,
    topk: int,
    mesh,
    profile_dir: str | None,
    max_chunks: int | None,
    device_ready: bool = False,
    coal=None,
):
    from ..parallel import mesh as mesh_lib
    from ..parallel.step import make_parallel_step
    from . import checkpoint as ckpt
    from .metrics import Profiler, ThroughputMeter

    # mesh is always resolved by _run_core (it needs it for the prefetch
    # pack closures) before this is called
    batch_size = mesh_lib.pad_batch_size(cfg.batch_size, mesh, cfg.mesh_axis)
    if packed.bindings_out and batch_size < 2:
        from ..errors import AnalysisError

        raise AnalysisError(
            "batch_size must be >= 2 when out-direction access-groups are "
            "bound: one connection line can emit two ACL evaluations"
        )

    stacked = cfg.layout == "stacked"
    lane = 0
    if stacked:
        from ..hostside.pack import GroupBuffer
        from ..parallel.step import make_parallel_step_stacked

        lane = cfg.stacked_lane or max(1, cfg.batch_size // max(1, packed.n_acls))
        lane = mesh_lib.pad_batch_size(lane, mesh, cfg.mesh_axis)
        dev_rules = pipeline.ship_ruleset_stacked(packed)
        step = make_parallel_step_stacked(mesh, cfg, packed.n_keys)
        gbuf = GroupBuffer(max(packed.n_acls, 1), lane)
    else:
        dev_rules = pipeline.ship_ruleset(packed, match_impl=cfg.match_impl)
        step = make_parallel_step(mesh, cfg, packed.n_keys)
        gbuf = None
    # IPv6 side path: sources that parse text stage v6 evaluations in a
    # separate buffer (take_v6); full [TUPLE6_COLS, batch] chunks step
    # through the v6 device program into the SAME registers.  Partial
    # buffers flush at checkpoints and end-of-stream, so snapshots never
    # leave consumed lines unstepped.
    step6 = None
    dev_rules6 = None
    if packed.has_v6 and (
        hasattr(source, "take_v6") or hasattr(source, "batches6")
    ):
        from ..parallel.step import make_parallel_step6

        dev_rules6 = pipeline.ship_ruleset6(packed)
        step6 = make_parallel_step6(mesh, cfg, packed.n_keys)
    buf6 = None
    fill6 = 0
    packer = source.packer
    wire_src = getattr(source, "yields_wire", False)
    #: input rows already carry weights (a coalesced .rawire file): the
    #: grouped compactor must preserve them, and resume offsets count
    #: STORED (unique) rows — a distinct unit from a plain wire file's.
    wire_weighted = getattr(source, "yields_wire_weighted", False)
    #: rows fed to the group buffer may carry weights > 1 (the coalescer
    #: was created — even auto-disabled runs buffered weighted rows
    #: during the sampling window — or the input file is weighted)
    weighted_rows = coal is not None or wire_weighted
    if wire_weighted:
        _check_weighted_input_config(cfg)
    # wire offsets count evaluation rows, text offsets count raw lines —
    # the same snapshot must not resume across input kinds (nor may a
    # weighted wire file's stored-row offsets resume a plain file's)
    fp = ckpt.fingerprint(packed, cfg, mesh_lib.data_extent(mesh), lane) + (
        ("-wirew" if wire_weighted else "-wire") if wire_src else ""
    )
    lines_consumed = 0
    n_chunks = 0

    snap = ckpt.load(cfg.checkpoint_dir) if cfg.resume else None
    if snap is not None:
        if snap.fingerprint != fp:
            raise ckpt.CheckpointMismatch(
                f"snapshot in {cfg.checkpoint_dir!r} was taken with a different "
                "ruleset, sketch geometry, batch size, or device count; "
                "refusing to merge"
            )
        state = ckpt.state_of(
            snap, lambda v: jax.device_put(v, mesh_lib.replicated(mesh))
        )
        tracker = ckpt.restore_tracker(snap, cfg.sketch.topk_capacity)
        source.set_counts(snap.parsed, snap.skipped)
        _restore_v6_digests(source, snap)
        lines_consumed = snap.lines_consumed
        n_chunks = snap.n_chunks
    else:
        state = pipeline.init_state(packed.n_keys, cfg)
        tracker = TopKTracker(cfg.sketch.topk_capacity)

    def drain(out: pipeline.ChunkOut) -> None:
        tracker.offer_chunk(
            np.asarray(out.cand_acl), np.asarray(out.cand_src), np.asarray(out.cand_est)
        )

    def save_snapshot() -> None:
        nonlocal last_snap_chunks
        # Stacked layout: step any buffered lines out first so the
        # registers cover exactly lines_consumed (the buffer holds lines
        # back until an ACL's lane fills; a snapshot with lines in limbo
        # would silently drop them on resume).
        if gbuf is not None:
            for grouped in gbuf.flush():
                run_grouped(grouped)
        flush_v6()
        last_snap_chunks = n_chunks
        while pending:
            drain(pending.popleft())
        pipeline.sync_state(state)
        ckpt.save(
            cfg.checkpoint_dir,
            ckpt.snapshot_of(
                state,
                lines_consumed=lines_consumed,
                n_chunks=n_chunks,
                parsed=packer.parsed,
                skipped=packer.skipped,
                tracker=tracker,
                fingerprint=fp,
                extra=_v6_digest_extra(source, tracker),
            ),
        )

    # One-time jit/compile + warmup priced SEPARATELY from the sustained
    # rate (VERDICT r5 Weak #1; measurement discipline in DispatchTimer)
    from .metrics import DispatchTimer

    _dispatch = DispatchTimer()
    _first_dispatch = _dispatch.first

    def run_chunk(batch_dev) -> None:
        # salt = chunk index: re-randomizes candidate-table slots per
        # chunk (no persistent talker collisions) yet replays exactly on
        # resume since n_chunks is restored from the snapshot
        nonlocal state, n_chunks
        state, out = _first_dispatch("v4", step, state, dev_rules, batch_dev, n_chunks)
        pending.append(out)
        if len(pending) > 2:
            drain(pending.popleft())
        n_chunks += 1

    def run_grouped(grouped_np: np.ndarray) -> None:
        # grouped batches also cross the wire bit-packed (16 B/line; the
        # weighted variant adds the 4-byte weights row — rows that may
        # carry weights MUST take it, or compact_grouped's 1-bit valid
        # would silently crush a weight-w row down to one line)
        with obs.span("ingest.pack"):
            wire = (
                pack_mod.compact_grouped_w(grouped_np)
                if weighted_rows
                else pack_mod.compact_grouped(grouped_np)
            )
            batch_dev = mesh_lib.shard_grouped(mesh, wire, cfg.mesh_axis)
        run_chunk(batch_dev)

    def run_chunk6(batch6_np: np.ndarray) -> None:
        nonlocal state, n_chunks
        if coal is not None and coal.enabled():
            # v6 chunks coalesce at step time: tuple batches carry the
            # weights in T6_VALID (no layout change), wire-v2 sections
            # grow the weights row (WIRE6W_COLS)
            if batch6_np.shape[0] == pack_mod.TUPLE6_COLS:
                batch6_np = coal.tuple6(batch6_np)
            else:
                batch6_np = coal.wire6(batch6_np)
        state, out = _first_dispatch(
            "v6", step6, state, dev_rules6,
            mesh_lib.shard_batch(mesh, batch6_np, cfg.mesh_axis), n_chunks,
        )
        pending.append(out)
        if len(pending) > 2:
            drain(pending.popleft())
        n_chunks += 1

    def stage_v6() -> None:
        # pull staged v6 rows from the source; step full chunks (text
        # sources only — wire v6 rows arrive via the phase-2 batches6)
        nonlocal buf6, fill6
        if not hasattr(source, "take_v6"):
            return
        rows = source.take_v6()
        i = 0
        while i < len(rows):
            if buf6 is None:
                buf6 = np.zeros(
                    (pack_mod.TUPLE6_COLS, batch_size), dtype=np.uint32
                )
            take = min(batch_size - fill6, len(rows) - i)
            buf6[:, fill6:fill6 + take] = np.asarray(
                rows[i:i + take], dtype=np.uint32
            ).T
            fill6 += take
            i += take
            if fill6 == batch_size:
                run_chunk6(buf6)  # fresh array allocated next fill
                buf6 = None
                fill6 = 0

    def flush_v6() -> None:
        # partial v6 chunk (padding columns carry valid=0) — called at
        # checkpoints and end-of-stream so consumed lines are never in
        # limbo across a snapshot
        nonlocal buf6, fill6
        if step6 is None:
            return
        stage_v6()
        if fill6:
            run_chunk6(buf6)
            buf6 = None
            fill6 = 0

    # Candidates drain with a 2-chunk lag: by the time chunk N-2's arrays
    # are fetched, their compute is long done, so the host never stalls on
    # the device — and memory stays O(1) chunks instead of O(n_chunks).
    pending: deque[pipeline.ChunkOut] = deque()
    lines_at_start = lines_consumed  # nonzero after resume
    meter = ThroughputMeter(cfg.report_every_chunks)
    chunks_this_run = 0
    last_snap_chunks = n_chunks  # snapshot cadence is device chunks SINCE
    with Profiler(profile_dir):  # the last save (stacked emits unevenly)
        for batch_np, n_raw_lines in source.batches(lines_consumed, batch_size):
            if batch_np is None:
                # zero-valid text batch (mostly-v6/unparseable stretch):
                # account the raw lines and drain staged v6 rows, but skip
                # the all-invalid v4 device step entirely.  Still ticks
                # chunks_this_run so max_chunks crash simulation aborts at
                # the same source-batch boundary it always did.
                lines_consumed += n_raw_lines
                meter.tick(n_raw_lines)
                if step6 is not None:
                    stage_v6()
                chunks_this_run += 1
                if max_chunks is not None and chunks_this_run >= max_chunks:
                    aborted = True
                    break
                continue
            if gbuf is not None:
                # bucket by ACL; grouped batches emit when a lane fills.
                # Coalescing compacts the batch BEFORE bucketing, so
                # lanes fill at the unique-row rate — more raw lines per
                # grouped device chunk.  (Emission cadence therefore
                # shifts vs the uncoalesced run; registers are cadence-
                # invariant, and the single-emission regime — lane >=
                # per-ACL rows — keeps even candidates identical,
                # DESIGN §11.)
                cols = (
                    pack_mod.expand_batch(batch_np) if wire_src else batch_np
                )
                if coal is not None and coal.enabled():
                    cols = coal.tuple4(cols, pad=False)
                for grouped in gbuf.add(np.ascontiguousarray(cols.T)):
                    run_grouped(grouped)
            elif device_ready:
                # the ingest pipeline already bit-packed the batch and
                # issued its async sharded device_put in the producer
                # thread; the H2D transfer has been overlapping earlier
                # steps since then
                run_chunk(batch_np)
            else:
                # ship the bit-packed wire layout: host->device transfer
                # is the narrowest stage on PCIe-starved links, and the
                # device unpack is three VPU shifts (pipeline.batch_cols)
                with obs.span("ingest.pack"):
                    if coal is not None and coal.enabled():
                        wire = (
                            coal.wire4(batch_np)
                            if wire_src
                            else pack_mod.compact_batch_w(coal.tuple4(batch_np))
                        )
                    else:
                        wire = (
                            batch_np if wire_src
                            else pack_mod.compact_batch(batch_np)
                        )
                    batch_dev = mesh_lib.shard_batch(mesh, wire, cfg.mesh_axis)
                run_chunk(batch_dev)
            if step6 is not None:
                stage_v6()
            lines_consumed += n_raw_lines
            chunks_this_run += 1
            meter.tick(n_raw_lines)
            if (
                cfg.checkpoint_every_chunks
                and n_chunks - last_snap_chunks >= cfg.checkpoint_every_chunks
            ):
                save_snapshot()
            if max_chunks is not None and chunks_this_run >= max_chunks:
                aborted = True
                break
        else:
            aborted = False
    if gbuf is not None:
        # Drain buffered lines (padded grouped batches) — also on a
        # max_chunks abort: those lines are already in lines_consumed and
        # the packer counters, so leaving them unstepped would return a
        # report whose totals claim lines the registers never saw.  (The
        # crash simulation lives in the SKIPPED final snapshot below, not
        # in losing buffered work from the returned report.)
        for grouped in gbuf.flush():
            run_grouped(grouped)
    # v6 rows buffered from consumed lines must step for the same reason
    # the grouped buffer drains above (totals already claim those lines)
    flush_v6()

    # Phase 2 — wire-v2 v6 section: the v6 rows of a .rawire input are
    # stored after every v4 block and consume here, with resume offsets
    # continuing over the concatenated row stream.
    b6fn = getattr(source, "batches6", None)
    if b6fn is not None and step6 is not None and not aborted:
        skip6 = max(0, lines_at_start - source.n4_rows)
        for b6, n_rows6 in b6fn(skip6, batch_size):
            # raw numpy in: run_chunk6 does the single shard_batch itself
            run_chunk6(b6)
            lines_consumed += n_rows6
            chunks_this_run += 1
            meter.tick(n_rows6)
            if (
                cfg.checkpoint_every_chunks
                and n_chunks - last_snap_chunks >= cfg.checkpoint_every_chunks
            ):
                save_snapshot()
            if max_chunks is not None and chunks_this_run >= max_chunks:
                aborted = True
                break

    # device_get-based sync, NOT block_until_ready: the remote-tunnel PJRT
    # plugin returns immediately from block_until_ready on shard_map
    # outputs, which would let elapsed() be captured while chunks are
    # still executing (a silently optimistic lines_per_sec).
    pipeline.sync_state(state)
    elapsed = meter.elapsed()
    while pending:
        drain(pending.popleft())
    # a max_chunks stop simulates a crash: only periodic snapshots survive
    if cfg.checkpoint_every_chunks and not aborted:
        save_snapshot()

    # lines_total/matched/skipped/chunks are cumulative across resumes;
    # throughput is this run's lines over this run's wall time only.
    # lines_matched counts ACL evaluations (a connection line bound to
    # both an in and an out ACL contributes two); lines_skipped counts
    # raw lines that produced no evaluation.
    lines_this_run = lines_consumed - lines_at_start
    compile_sec = _dispatch.compile_sec()
    sustained = elapsed - compile_sec
    totals = {
        "lines_total": lines_consumed,
        "lines_matched": packer.parsed,
        "lines_skipped": packer.skipped,
        "chunks": n_chunks,
        "elapsed_sec": round(elapsed, 4),
        "lines_per_sec": round(lines_this_run / elapsed, 1) if elapsed > 0 else 0.0,
        # one-time jit trace + XLA compile (first dispatch of each device
        # program), priced separately: two committed e2e artifacts once
        # disagreed 7.7x purely on how much of the run was compile
        "compile_sec": round(compile_sec, 4),
        "sustained_lines_per_sec": (
            round(lines_this_run / sustained, 1) if sustained > 0 else 0.0
        ),
        # the meter's own cumulative numbers, folded into the report so
        # downstream artifacts stop re-deriving them from stderr lines
        "throughput": meter.summary(),
    }
    stats_fn = getattr(source, "ingest_stats", None)
    if stats_fn is not None:
        # per-stage overlap accounting: parse-starved vs device-bound
        totals["ingest"] = stats_fn()
    lat_fn = getattr(source, "latency_summary", None)
    if lat_fn is not None:
        lat = lat_fn()
        if lat:
            # produce->commit batch-latency percentiles (DESIGN §20)
            totals["latency"] = lat
    if coal is not None:
        # raw-vs-unique accounting + the auto decision, in the report so
        # artifacts can state the compaction ratio a run actually saw
        totals["coalesce"] = coal.summary()
    dp = devprof.finalize_if_armed()
    if dp is not None:
        # per-stage device attribution of the capture window (DESIGN
        # §14); VOLATILE in the identity tests — armed vs disarmed
        # reports stay bit-identical outside this block
        totals["devprof"] = dp
    patch = getattr(source, "totals_patch", None)
    if patch is not None:
        # wire input: restore the converter's raw-line accounting once the
        # whole file is consumed (rows != raw text lines)
        totals.update(patch(not aborted))
    return pipeline.finalize(
        state, packed, cfg, tracker, topk=topk, totals=totals,
        v6_digests=getattr(source, "v6_digests", None),
    )
