"""Streaming driver: host text -> packed batches -> fused device steps.

The rebuild of the reference's job loop (SURVEY.md §4.2): where Hadoop
splits HDFS input across mapper processes, this driver cuts the unbounded
log stream into fixed-size batches (constant device memory, one compiled
program — SURVEY.md §6 "long-context" note), packs them on host, and feeds
the jitted analysis step.

Overlap comes from JAX's async dispatch: ``step`` returns immediately with
futures, so host parsing of chunk N+1 runs while the device crunches chunk
N.  Top-K candidates drain through a short lag queue so fetching them
never synchronises the host with the in-flight chunk.
"""

from __future__ import annotations

from collections import deque
from collections.abc import Iterable, Iterator

import jax
import numpy as np

from ..config import AnalysisConfig
from ..hostside.pack import LinePacker, PackedRuleset
from ..models import pipeline
from ..ops.topk import TopKTracker


_SENTINEL = object()


def chunked(it: Iterable[str], size: int) -> Iterator[list[str]]:
    buf: list[str] = []
    for x in it:
        buf.append(x)
        if len(buf) == size:
            yield buf
            buf = []
    if buf:
        yield buf


class _TextSource:
    """Batch source over an iterable of decoded lines (pure-Python parse)."""

    def __init__(self, packed: PackedRuleset, lines: Iterable[str]):
        self.packer = LinePacker(packed)
        self._lines = lines

    def set_counts(self, parsed: int, skipped: int) -> None:
        self.packer.parsed, self.packer.skipped = parsed, skipped

    def batches(self, skip_lines: int, batch_size: int) -> Iterator[tuple[np.ndarray, int]]:
        it = iter(self._lines)
        skipped_ok = 0
        for _ in range(skip_lines):
            if next(it, _SENTINEL) is _SENTINEL:
                break
            skipped_ok += 1
        if skipped_ok < skip_lines:
            from ..errors import ResumeInputMismatch

            raise ResumeInputMismatch(
                f"snapshot consumed {skip_lines} lines but the input "
                f"stream has only {skipped_ok}; wrong or truncated log input"
            )
        for chunk in chunked(it, batch_size):
            batch_np = np.ascontiguousarray(
                self.packer.pack_lines(chunk, batch_size=batch_size).T
            )
            yield batch_np, len(chunk)


class _FileSource:
    """Batch source over syslog file(s) via the native C++ parser."""

    def __init__(self, packed: PackedRuleset, paths: list[str]):
        from ..hostside import fastparse

        self.packer = fastparse.NativePacker(packed)
        self._paths = paths

    def set_counts(self, parsed: int, skipped: int) -> None:
        self.packer.set_counts(parsed, skipped)

    def batches(self, skip_lines: int, batch_size: int) -> Iterator[tuple[np.ndarray, int]]:
        from ..hostside import fastparse

        return fastparse.batches_from_files(
            self._paths, self.packer, batch_size, skip_lines=skip_lines
        )


def run_stream(
    packed: PackedRuleset,
    lines: Iterable[str],
    cfg: AnalysisConfig,
    *,
    topk: int = 10,
    mesh=None,
    profile_dir: str | None = None,
    max_chunks: int | None = None,
):
    """Run the full analysis over a stream of raw syslog lines; return Report.

    With a multi-device mesh (or by default when several devices are
    visible), the batch shards over the data axis and registers merge via
    ICI collectives; on one device this degenerates to the single-chip
    step.  Results are bit-identical either way (mergeable registers).

    With ``cfg.checkpoint_every_chunks`` set, an atomic (offset, registers)
    snapshot lands in ``cfg.checkpoint_dir`` every N chunks; with
    ``cfg.resume``, an existing snapshot is loaded and that many raw input
    lines are skipped before streaming continues — final registers are
    bit-identical to an uninterrupted run (mergeable state).

    ``max_chunks`` stops after N chunks (fault-injection in tests; also a
    cheap "analyze a prefix" knob).
    """
    return _run_core(
        packed,
        _TextSource(packed, lines),
        cfg,
        topk=topk,
        mesh=mesh,
        profile_dir=profile_dir,
        max_chunks=max_chunks,
    )


def run_stream_file(
    packed: PackedRuleset,
    paths: str | list[str],
    cfg: AnalysisConfig,
    *,
    native: bool | None = None,
    topk: int = 10,
    mesh=None,
    profile_dir: str | None = None,
    max_chunks: int | None = None,
):
    """Analyze syslog file(s), using the native C++ parser when available.

    ``native=None`` auto-selects: the C++ fast path if its library loads
    (building it on first use), else the pure-Python line path.  Results
    are identical either way; only host-side parse throughput differs.
    """
    from ..hostside import fastparse

    if isinstance(paths, str):
        paths = [paths]
    if native is None:
        native = fastparse.available()
    if native:
        source = _FileSource(packed, paths)
    else:
        def _lines():
            for path in paths:
                with open(path, "r", encoding="utf-8", errors="replace") as f:
                    yield from f

        source = _TextSource(packed, _lines())
    return _run_core(
        packed,
        source,
        cfg,
        topk=topk,
        mesh=mesh,
        profile_dir=profile_dir,
        max_chunks=max_chunks,
    )


def _run_core(
    packed: PackedRuleset,
    source,
    cfg: AnalysisConfig,
    *,
    topk: int,
    mesh,
    profile_dir: str | None,
    max_chunks: int | None,
):
    from ..parallel import mesh as mesh_lib
    from ..parallel.step import make_parallel_step
    from . import checkpoint as ckpt
    from .metrics import Profiler, ThroughputMeter

    if mesh is None:
        mesh = mesh_lib.make_mesh(axis=cfg.mesh_axis)
    batch_size = mesh_lib.pad_batch_size(cfg.batch_size, mesh, cfg.mesh_axis)

    dev_rules = pipeline.ship_ruleset(packed, match_impl=cfg.match_impl)
    step = make_parallel_step(mesh, cfg, packed.n_keys)
    packer = source.packer
    fp = ckpt.fingerprint(packed, cfg, mesh.shape[cfg.mesh_axis])
    lines_consumed = 0
    n_chunks = 0

    snap = ckpt.load(cfg.checkpoint_dir) if cfg.resume else None
    if snap is not None:
        if snap.fingerprint != fp:
            raise ckpt.CheckpointMismatch(
                f"snapshot in {cfg.checkpoint_dir!r} was taken with a different "
                "ruleset, sketch geometry, batch size, or device count; "
                "refusing to merge"
            )
        state = pipeline.AnalysisState(
            **{k: jax.device_put(v, mesh_lib.replicated(mesh)) for k, v in snap.arrays.items()}
        )
        tracker = ckpt.restore_tracker(snap, cfg.sketch.topk_capacity)
        source.set_counts(snap.parsed, snap.skipped)
        lines_consumed = snap.lines_consumed
        n_chunks = snap.n_chunks
    else:
        state = pipeline.init_state(packed.n_keys, cfg)
        tracker = TopKTracker(cfg.sketch.topk_capacity)

    def drain(out: pipeline.ChunkOut) -> None:
        tracker.offer_chunk(
            np.asarray(out.cand_acl), np.asarray(out.cand_src), np.asarray(out.cand_est)
        )

    def save_snapshot() -> None:
        while pending:
            drain(pending.popleft())
        jax.block_until_ready(state)
        ckpt.save(
            cfg.checkpoint_dir,
            ckpt.Snapshot(
                arrays={
                    k: np.asarray(jax.device_get(getattr(state, k)))
                    for k in pipeline.AnalysisState._fields
                },
                lines_consumed=lines_consumed,
                n_chunks=n_chunks,
                parsed=packer.parsed,
                skipped=packer.skipped,
                tracker_tables=tracker.tables(),
                fingerprint=fp,
            ),
        )

    # Candidates drain with a 2-chunk lag: by the time chunk N-2's arrays
    # are fetched, their compute is long done, so the host never stalls on
    # the device — and memory stays O(1) chunks instead of O(n_chunks).
    pending: deque[pipeline.ChunkOut] = deque()
    lines_at_start = packer.parsed + packer.skipped  # nonzero after resume
    meter = ThroughputMeter(cfg.report_every_chunks)
    chunks_this_run = 0
    with Profiler(profile_dir):
        for batch_np, n_raw_lines in source.batches(lines_consumed, batch_size):
            batch = mesh_lib.shard_batch(mesh, batch_np, cfg.mesh_axis)
            # salt = chunk index: re-randomizes candidate-table slots per
            # chunk (no persistent talker collisions) yet replays exactly
            # on resume since n_chunks is restored from the snapshot
            state, out = step(state, dev_rules, batch, n_chunks)
            pending.append(out)
            if len(pending) > 2:
                drain(pending.popleft())
            lines_consumed += n_raw_lines
            n_chunks += 1
            chunks_this_run += 1
            meter.tick(n_raw_lines)
            if cfg.checkpoint_every_chunks and n_chunks % cfg.checkpoint_every_chunks == 0:
                save_snapshot()
            if max_chunks is not None and chunks_this_run >= max_chunks:
                aborted = True
                break
        else:
            aborted = False

    jax.block_until_ready(state)
    elapsed = meter.elapsed()
    while pending:
        drain(pending.popleft())
    # a max_chunks stop simulates a crash: only periodic snapshots survive
    if cfg.checkpoint_every_chunks and not aborted:
        save_snapshot()

    # lines_total/matched/skipped/chunks are cumulative across resumes;
    # throughput is this run's lines over this run's wall time only.
    lines_total = packer.parsed + packer.skipped
    lines_this_run = lines_total - lines_at_start
    totals = {
        "lines_total": lines_total,
        "lines_matched": packer.parsed,
        "lines_skipped": packer.skipped,
        "chunks": n_chunks,
        "elapsed_sec": round(elapsed, 4),
        "lines_per_sec": round(lines_this_run / elapsed, 1) if elapsed > 0 else 0.0,
    }
    return pipeline.finalize(
        state, packed, cfg, tracker, topk=topk, totals=totals
    )
