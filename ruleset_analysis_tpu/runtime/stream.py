"""Streaming driver: host text -> packed batches -> fused device steps.

The rebuild of the reference's job loop (SURVEY.md §4.2): where Hadoop
splits HDFS input across mapper processes, this driver cuts the unbounded
log stream into fixed-size batches (constant device memory, one compiled
program — SURVEY.md §6 "long-context" note), packs them on host, and feeds
the jitted analysis step.

Overlap comes from JAX's async dispatch: ``step`` returns immediately with
futures, so host parsing of chunk N+1 runs while the device crunches chunk
N.  Top-K candidates drain through a short lag queue so fetching them
never synchronises the host with the in-flight chunk.
"""

from __future__ import annotations

from collections import deque
from collections.abc import Iterable, Iterator

import jax
import numpy as np

from ..config import AnalysisConfig
from ..hostside.pack import LinePacker, PackedRuleset
from ..models import pipeline
from ..ops.topk import TopKTracker


_SENTINEL = object()


def chunked(it: Iterable[str], size: int) -> Iterator[list[str]]:
    buf: list[str] = []
    for x in it:
        buf.append(x)
        if len(buf) == size:
            yield buf
            buf = []
    if buf:
        yield buf


def run_stream(
    packed: PackedRuleset,
    lines: Iterable[str],
    cfg: AnalysisConfig,
    *,
    topk: int = 10,
    mesh=None,
    profile_dir: str | None = None,
    max_chunks: int | None = None,
):
    """Run the full analysis over a stream of raw syslog lines; return Report.

    With a multi-device mesh (or by default when several devices are
    visible), the batch shards over the data axis and registers merge via
    ICI collectives; on one device this degenerates to the single-chip
    step.  Results are bit-identical either way (mergeable registers).

    With ``cfg.checkpoint_every_chunks`` set, an atomic (offset, registers)
    snapshot lands in ``cfg.checkpoint_dir`` every N chunks; with
    ``cfg.resume``, an existing snapshot is loaded and that many raw input
    lines are skipped before streaming continues — final registers are
    bit-identical to an uninterrupted run (mergeable state).

    ``max_chunks`` stops after N chunks (fault-injection in tests; also a
    cheap "analyze a prefix" knob).
    """
    from ..parallel import mesh as mesh_lib
    from ..parallel.step import make_parallel_step
    from . import checkpoint as ckpt
    from .metrics import Profiler, ThroughputMeter

    if mesh is None:
        mesh = mesh_lib.make_mesh(axis=cfg.mesh_axis)
    batch_size = mesh_lib.pad_batch_size(cfg.batch_size, mesh, cfg.mesh_axis)

    dev_rules = pipeline.ship_ruleset(packed)
    step = make_parallel_step(mesh, cfg, packed.n_keys)
    packer = LinePacker(packed)
    fp = ckpt.fingerprint(packed, cfg, mesh.shape[cfg.mesh_axis])
    lines_consumed = 0
    n_chunks = 0

    snap = ckpt.load(cfg.checkpoint_dir) if cfg.resume else None
    if snap is not None:
        if snap.fingerprint != fp:
            raise ckpt.CheckpointMismatch(
                f"snapshot in {cfg.checkpoint_dir!r} was taken with a different "
                "ruleset or sketch geometry; refusing to merge"
            )
        state = pipeline.AnalysisState(
            **{k: jax.device_put(v, mesh_lib.replicated(mesh)) for k, v in snap.arrays.items()}
        )
        tracker = ckpt.restore_tracker(snap, cfg.sketch.topk_capacity)
        packer.parsed, packer.skipped = snap.parsed, snap.skipped
        lines_consumed = snap.lines_consumed
        n_chunks = snap.n_chunks
        it = iter(lines)
        skipped_ok = 0
        for _ in range(lines_consumed):
            if next(it, _SENTINEL) is _SENTINEL:
                break
            skipped_ok += 1
        if skipped_ok < lines_consumed:
            from ..errors import ResumeInputMismatch

            raise ResumeInputMismatch(
                f"snapshot consumed {lines_consumed} lines but the input "
                f"stream has only {skipped_ok}; wrong or truncated log input"
            )
        lines = it
    else:
        state = pipeline.init_state(packed.n_keys, cfg)
        tracker = TopKTracker(cfg.sketch.topk_capacity)

    def drain(out: pipeline.ChunkOut) -> None:
        tracker.offer_chunk(
            np.asarray(out.cand_acl), np.asarray(out.cand_src), np.asarray(out.cand_est)
        )

    def save_snapshot() -> None:
        while pending:
            drain(pending.popleft())
        jax.block_until_ready(state)
        ckpt.save(
            cfg.checkpoint_dir,
            ckpt.Snapshot(
                arrays={
                    k: np.asarray(jax.device_get(getattr(state, k)))
                    for k in pipeline.AnalysisState._fields
                },
                lines_consumed=lines_consumed,
                n_chunks=n_chunks,
                parsed=packer.parsed,
                skipped=packer.skipped,
                tracker_tables=tracker.tables(),
                fingerprint=fp,
            ),
        )

    # Candidates drain with a 2-chunk lag: by the time chunk N-2's arrays
    # are fetched, their compute is long done, so the host never stalls on
    # the device — and memory stays O(1) chunks instead of O(n_chunks).
    pending: deque[pipeline.ChunkOut] = deque()
    lines_at_start = packer.parsed + packer.skipped  # nonzero after resume
    meter = ThroughputMeter(cfg.report_every_chunks)
    chunks_this_run = 0
    with Profiler(profile_dir):
        for chunk in chunked(lines, batch_size):
            batch_np = np.ascontiguousarray(
                packer.pack_lines(chunk, batch_size=batch_size).T
            )
            batch = mesh_lib.shard_batch(mesh, batch_np, cfg.mesh_axis)
            state, out = step(state, dev_rules, batch)
            pending.append(out)
            if len(pending) > 2:
                drain(pending.popleft())
            lines_consumed += len(chunk)
            n_chunks += 1
            chunks_this_run += 1
            meter.tick(len(chunk))
            if cfg.checkpoint_every_chunks and n_chunks % cfg.checkpoint_every_chunks == 0:
                save_snapshot()
            if max_chunks is not None and chunks_this_run >= max_chunks:
                aborted = True
                break
        else:
            aborted = False

    jax.block_until_ready(state)
    elapsed = meter.elapsed()
    while pending:
        drain(pending.popleft())
    # a max_chunks stop simulates a crash: only periodic snapshots survive
    if cfg.checkpoint_every_chunks and not aborted:
        save_snapshot()

    # lines_total/matched/skipped/chunks are cumulative across resumes;
    # throughput is this run's lines over this run's wall time only.
    lines_total = packer.parsed + packer.skipped
    lines_this_run = lines_total - lines_at_start
    totals = {
        "lines_total": lines_total,
        "lines_matched": packer.parsed,
        "lines_skipped": packer.skipped,
        "chunks": n_chunks,
        "elapsed_sec": round(elapsed, 4),
        "lines_per_sec": round(lines_this_run / elapsed, 1) if elapsed > 0 else 0.0,
    }
    return pipeline.finalize(
        state, packed, cfg, tracker, topk=topk, totals=totals
    )
