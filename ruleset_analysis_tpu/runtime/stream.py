"""Streaming driver: host text -> packed batches -> fused device steps.

The rebuild of the reference's job loop (SURVEY.md §4.2): where Hadoop
splits HDFS input across mapper processes, this driver cuts the unbounded
log stream into fixed-size batches (constant device memory, one compiled
program — SURVEY.md §6 "long-context" note), packs them on host, and feeds
the jitted analysis step.

Overlap comes from JAX's async dispatch: ``step`` returns immediately with
futures, so host parsing of chunk N+1 runs while the device crunches chunk
N.  Top-K candidates are kept as device arrays and drained once at the end
(or at checkpoint boundaries) to avoid per-chunk synchronisation.
"""

from __future__ import annotations

import time
from collections.abc import Iterable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ..config import AnalysisConfig
from ..hostside.pack import LinePacker, PackedRuleset
from ..models import pipeline
from ..ops.topk import TopKTracker


def chunked(it: Iterable[str], size: int) -> Iterator[list[str]]:
    buf: list[str] = []
    for x in it:
        buf.append(x)
        if len(buf) == size:
            yield buf
            buf = []
    if buf:
        yield buf


def run_stream(
    packed: PackedRuleset,
    lines: Iterable[str],
    cfg: AnalysisConfig,
    *,
    topk: int = 10,
):
    """Run the full analysis over a stream of raw syslog lines; return Report."""
    dev_rules = pipeline.ship_ruleset(packed)
    state = pipeline.init_state(packed.n_keys, cfg)
    step = pipeline.make_step(cfg, packed.n_keys)
    packer = LinePacker(packed)
    tracker = TopKTracker(cfg.sketch.topk_capacity)

    chunk_outs: list[pipeline.ChunkOut] = []
    n_chunks = 0
    t0 = time.perf_counter()
    for chunk in chunked(lines, cfg.batch_size):
        batch_np = np.ascontiguousarray(
            packer.pack_lines(chunk, batch_size=cfg.batch_size).T
        )
        batch = jnp.asarray(batch_np)
        state, out = step(state, dev_rules, batch)
        chunk_outs.append(out)
        n_chunks += 1

    jax.block_until_ready(state)
    elapsed = time.perf_counter() - t0

    for out in chunk_outs:
        tracker.offer_chunk(
            np.asarray(out.cand_acl), np.asarray(out.cand_src), np.asarray(out.cand_est)
        )

    lines_total = packer.parsed + packer.skipped
    totals = {
        "lines_total": lines_total,
        "lines_matched": packer.parsed,
        "lines_skipped": packer.skipped,
        "chunks": n_chunks,
        "elapsed_sec": round(elapsed, 4),
        "lines_per_sec": round(lines_total / elapsed, 1) if elapsed > 0 else 0.0,
    }
    return pipeline.finalize(
        state, packed, cfg, tracker, topk=topk, totals=totals
    )
