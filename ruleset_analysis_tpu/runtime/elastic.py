"""Elastic recovery: automatic cluster re-formation after peer death.

The reference's Hadoop runtime re-executes failed tasks *automatically*
(YARN restarts a mapper whose node died and the job completes); our
distributed runtime could only detect a dead peer and abort cleanly,
leaving a human to restart.  This module closes that gap — the SURVEY §3b
"elastic/retry analog" promoted from manual to automatic:

- every worker process runs under an :class:`ElasticSupervisor` that
  registers a filesystem heartbeat in a shared rendezvous directory and
  spawns the actual analysis worker as a child process per *generation*;
- the distributed chunk loop snapshots an **epoch-tagged, world-size-
  independent checkpoint** (replicated registers + a per-shard cursor
  manifest) into the shared ``epoch/`` directory at the configured
  cadence (stream.py ``save_epoch_snapshot``);
- when a peer dies, the survivors' collectives abort (jax heartbeat
  where supported; the supervisor's own watchdog — stale member
  heartbeats for whole-node death, per-generation failure markers for
  worker-only death — kills a wedged child as the version-proof
  backstop), the supervisors detect the loss, **re-elect** a coordinator
  (lowest surviving member tag), re-form ``jax.distributed`` at the
  surviving world size on a fresh port, and spawn the next generation;
- the new generation loads the epoch checkpoint, **re-splits the unread
  input shards** across the survivors (deterministic round-robin over the
  cursor manifest), and resumes.

Teardown of the failed ``jax.distributed`` cluster is by child-process
exit — the one teardown that can never wedge on a half-dead coordinator.

Because the registers are mergeable and order-invariant, the final
per-rule hit counts and the unused-rule report are **bit-identical** to an
uninterrupted run over the same shards, at any surviving world size (the
top-K talker candidate pool is chunk-boundary-sensitive by design and may
differ — the same caveat the feeder tier documents).

Rendezvous directory layout (shared filesystem)::

    elastic_dir/
      members/<tag>.hb        heartbeat file (mtime refreshed ~2x/sec)
      members/<tag>.job.json  this member's job spec for its workers
      epoch/                  epoch checkpoints (runtime/checkpoint.py)
      gen-<g>/join/<tag>      generation-g membership markers
      gen-<g>/plan.json       leader-written formation plan
      gen-<g>/worker-<t>.log  per-worker stdio capture

Liveness notes: every wait has a timeout, exhausting ``max_reforms``
aborts with the existing clean-abort behavior, and a member that misses a
formation (slow heartbeat) aborts rather than wedging the others.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

from ..errors import AnalysisError, EXIT_REFORM_BUDGET, StallError, exit_code_for
from . import faults, obs
from .metrics import RecoveryMeter

#: internal rc sentinel for a worker retired by a PLANNED scale event
#: (outside the kernel's exit-status range, so it can never collide with
#: a real worker rc or -signal)
SCALE_RC = -1001

#: seconds between heartbeat-file touches
HB_INTERVAL = 0.5
#: a member whose heartbeat is older than this is presumed dead (15 missed
#: beats: wide enough that host load spikes — a fleet of workers jitting
#: at once — don't read as death)
STALE_SEC = 7.5
#: after a peer is presumed dead, how long a still-running worker gets to
#: abort on its own (jax's heartbeat surface) before the supervisor kills it
KILL_GRACE_SEC = 10.0
#: generation-formation waits (join barrier, plan publication)
FORM_TIMEOUT_SEC = 180.0
#: dead-peer detection bound passed to jax.distributed (where supported)
JAX_HEARTBEAT_SEC = 10
#: cluster-formation bound: a planned member that died before joining must
#: not hold everyone in initialize() for jax's 300 s default
JAX_INIT_TIMEOUT_SEC = 60

#: child exit code that simulates abrupt node death (test fault injection:
#: the supervisor re-raises it with os._exit, taking the heartbeat with it)
DIE_RC = 77


class FormationTimeout(StallError):
    """A generation could not form within the rendezvous timeout.

    A StallError subclass: formation hanging past its bound is the
    distributed face of the same watchdog tier (CLI exit code 6)."""


class _PrevGenDone(Exception):
    """Internal: the previous generation finished while we headed into
    the next formation (a scale/death signal raced the final worker
    exits).  The run is complete; this member exits 0."""


# ---------------------------------------------------------------------------
# Cursor manifest + shard re-splitting
# ---------------------------------------------------------------------------


def manifest_of(snap) -> tuple[list[str] | None, dict[int, int], set[int]]:
    """(shards, cursors, done) from an epoch Snapshot (None -> empty)."""
    if snap is None or not snap.extra or "elastic" not in snap.extra:
        return None, {}, set()
    man = snap.extra["elastic"]
    return (
        list(man["shards"]),
        {int(k): int(v) for k, v in man["cursors"].items()},
        {int(i) for i in man["done"]},
    )


def assign_shards(
    shards: list[str],
    cursors: dict[int, int],
    done: set[int],
    world_size: int,
) -> list[list[tuple[int, str, int]]]:
    """Deterministic re-split of unread shard work across ``world_size`` ranks.

    Whole shards are the assignment unit (the HDFS-input-split analog); a
    partially-consumed shard travels with its cursor so the new owner
    resumes mid-file.  Round-robin over the remaining shards in index
    order — every worker computes the identical split from the shared
    manifest, so no coordination message is needed.
    """
    remaining = [i for i in range(len(shards)) if i not in done]
    out: list[list[tuple[int, str, int]]] = [[] for _ in range(world_size)]
    for pos, idx in enumerate(remaining):
        out[pos % world_size].append((idx, shards[idx], cursors.get(idx, 0)))
    return out


@dataclasses.dataclass
class ElasticRunSpec:
    """Everything stream.run_stream_file_distributed needs for one generation."""

    epoch_dir: str
    shards: list[str]  # the GLOBAL ordered shard list (identical everywhere)
    assignments: list[tuple[int, str, int]]  # this rank's (idx, path, start)
    snapshot: object | None  # checkpoint.Snapshot of the epoch, or None
    base_cursors: dict[int, int]  # manifest cursors at epoch load
    base_done: set[int]  # shards fully consumed before this generation
    epoch: int  # generation tag stamped into new snapshots
    die_after_batches: int | None = None  # TEST-ONLY crash injection
    pace_sec: float = 0.0  # TEST-ONLY offered-load throttle (autoscale drills)


# ---------------------------------------------------------------------------
# Rendezvous helpers
# ---------------------------------------------------------------------------


def _atomic_write_json(path: str, obj) -> None:
    """fsync'd write-then-rename; ``obj`` may be a pre-serialized string."""
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
    with os.fdopen(fd, "w", encoding="utf-8") as f:
        if isinstance(obj, str):
            f.write(obj)
        else:
            json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class _Heartbeat(threading.Thread):
    """Touches ``members/<tag>.hb`` until stopped (daemon: dies with us)."""

    def __init__(self, path: str):
        super().__init__(daemon=True, name="ra-heartbeat")
        self._path = path
        self._stop = threading.Event()

    def run(self) -> None:
        from ..errors import InjectedFault

        while not self._stop.is_set():
            try:
                # chaos site: this member's heartbeat silently stops
                # (network partition / node freeze) — the PEERS' staleness
                # watchdog must re-form without it, and this member must
                # abort when it finds itself outside the next formation
                faults.fire("elastic.heartbeat.drop", stop=self._stop)
            except InjectedFault:
                return  # stop touching forever: the partition persists
            try:
                with open(self._path, "a"):
                    os.utime(self._path, None)
            except OSError:
                pass
            self._stop.wait(HB_INTERVAL)

    def stop(self) -> None:
        self._stop.set()


# ---------------------------------------------------------------------------
# Supervisor
# ---------------------------------------------------------------------------


class ElasticSupervisor:
    """Per-process recovery supervisor: heartbeat, re-election, respawn.

    One supervisor runs in each of the job's N launcher processes (the
    ``run --distributed --elastic`` CLI path).  The analysis itself runs
    in a child process per generation, so tearing down a failed
    ``jax.distributed`` cluster is a child exit — never an in-process
    re-initialize that can wedge on a half-dead coordinator.
    """

    def __init__(
        self,
        elastic_dir: str,
        tag: int,
        n_procs: int,
        ruleset_prefix: str,
        shards: list[str],
        cfg,
        *,
        max_reforms: int = 2,
        topk: int = 10,
        native: bool | None = None,
        out_prefix: str | None = None,
        fault: dict | None = None,
        heartbeat_timeout: int = JAX_HEARTBEAT_SEC,
        coordinator_host: str | None = None,
        autoscale=None,  # config.AutoscaleConfig | None
    ):
        from ..hostside.wire import is_wire_file

        if not 0 <= tag < n_procs:
            raise AnalysisError(f"tag {tag} outside 0..{n_procs - 1}")
        wired = [p for p in shards if is_wire_file(p)]
        if wired:
            raise AnalysisError(
                f"--elastic re-splits text shards; {wired[0]!r} is a "
                ".rawire wire file (convert-tier elastic is not built yet)"
            )
        if cfg.checkpoint_every_chunks < 1:
            raise AnalysisError(
                "--elastic needs an epoch-checkpoint cadence; set "
                "--checkpoint-every N (recovery replays at most N chunks)"
            )
        self.dir = os.path.abspath(elastic_dir)
        self.tag = int(tag)
        self.n_procs = int(n_procs)
        self.max_reforms = int(max_reforms)
        # -- metrics-driven autoscaling (runtime/autoscale.py) ------------
        # the launcher pool is the PROVISIONED maximum: members outside
        # the active world park as warm standbys and join the next
        # formation when a scale-out (or a death) needs them
        self.autoscale = autoscale
        self._ladder: list[int] = []
        self._initial_world = int(n_procs)
        if autoscale is not None:
            from .autoscale import world_ladder

            max_w = autoscale.max_world or self.n_procs
            if max_w > self.n_procs:
                raise AnalysisError(
                    f"--autoscale-max {max_w} exceeds the provisioned "
                    f"launcher pool ({self.n_procs} members)"
                )
            self._ladder = world_ladder(autoscale.min_world, max_w)
            self._initial_world = autoscale.initial_world or autoscale.min_world
        self._scale_pending: dict | None = None
        self._scale_anchor: float | None = None
        # children always start fresh from the shared epoch dir; the
        # per-process --resume machinery must not engage
        self.cfg = cfg.replace(resume=False)
        self.job = {
            "ruleset": os.path.abspath(ruleset_prefix),
            "shards": [os.path.abspath(p) for p in shards],
            "cfg": self.cfg.to_dict(),
            "topk": int(topk),
            "native": native,
            "out": os.path.abspath(out_prefix) if out_prefix else None,
            "heartbeat_timeout": int(heartbeat_timeout),
            "init_timeout": JAX_INIT_TIMEOUT_SEC,
            "fault": fault,
            "autoscale": autoscale.to_dict() if autoscale is not None else None,
        }
        self.coordinator_host = coordinator_host or os.environ.get(
            "RA_ELASTIC_HOST", "127.0.0.1"
        )
        self.meter = RecoveryMeter()
        self.reforms_used = 0
        self.final_world: list[int] | None = None
        self._hb: _Heartbeat | None = None

    # -- paths ------------------------------------------------------------
    def _members_dir(self) -> str:
        return os.path.join(self.dir, "members")

    def _hb_path(self, tag: int) -> str:
        return os.path.join(self._members_dir(), f"{tag}.hb")

    def _gen_dir(self, gen: int) -> str:
        return os.path.join(self.dir, f"gen-{gen}")

    def _plan_path(self, gen: int) -> str:
        return os.path.join(self._gen_dir(gen), "plan.json")

    @property
    def epoch_dir(self) -> str:
        return os.path.join(self.dir, "epoch")

    def _scale_path(self) -> str:
        return os.path.join(self.dir, "scale.json")

    def _scale_log_path(self) -> str:
        return os.path.join(self.dir, "scale-log.jsonl")

    def _metrics_path(self, gen: int, tag: int) -> str:
        return os.path.join(self._gen_dir(gen), f"metrics-{tag}.jsonl")

    # -- membership -------------------------------------------------------
    def _fresh_members(self) -> set[int]:
        now = time.time()
        fresh = set()
        try:
            entries = os.listdir(self._members_dir())
        except OSError:
            return fresh
        for e in entries:
            if not e.endswith(".hb"):
                continue
            try:
                t = int(e[:-3])
                if now - os.path.getmtime(os.path.join(self._members_dir(), e)) < STALE_SEC:
                    fresh.add(t)
            except (ValueError, OSError):
                continue
        return fresh

    def _join(self, gen: int) -> None:
        d = os.path.join(self._gen_dir(gen), "join")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, str(self.tag)), "w") as f:
            f.write(str(os.getpid()))

    def _joined(self, gen: int) -> set[int]:
        d = os.path.join(self._gen_dir(gen), "join")
        try:
            return {int(e) for e in os.listdir(d) if e.isdigit()}
        except OSError:
            return set()

    def _mark_done(self, gen: int) -> None:
        """Success marker: parked standbys (and racing peers heading into
        the next formation) learn the run completed and exit 0."""
        d = os.path.join(self._gen_dir(gen), "done")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, str(self.tag)), "w") as f:
            f.write("")

    def _done(self, gen: int) -> bool:
        try:
            return bool(os.listdir(os.path.join(self._gen_dir(gen), "done")))
        except OSError:
            return False

    def _read_scale(self) -> dict | None:
        """The current scale request (atomic-written by the leader)."""
        try:
            with open(self._scale_path(), "r", encoding="utf-8") as f:
                req = json.load(f)
        except (OSError, ValueError):
            return None
        return req if isinstance(req, dict) else None

    def _mark_failed(self, gen: int) -> None:
        d = os.path.join(self._gen_dir(gen), "failed")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, str(self.tag)), "w") as f:
            f.write("")

    def _peer_failed(self, gen: int) -> bool:
        d = os.path.join(self._gen_dir(gen), "failed")
        try:
            return any(e.isdigit() and int(e) != self.tag for e in os.listdir(d))
        except OSError:
            return False

    # -- formation --------------------------------------------------------
    def _target_world(self, gen: int, avail: list[int]) -> tuple[int, int]:
        """Leader-side sizing: (active world size, consumed scale seq).

        The active size carries forward from the previous generation's
        plan, updated by a pending scale request (``scale.json`` with a
        seq the previous plan has not consumed) and clamped to the
        members actually available — a death below the requested world
        runs with what is left, and a parked standby is promoted to
        backfill a dead active member (warm-standby replacement).
        """
        if gen == 0:
            prev_size, seen = self._initial_world, 0
        else:
            try:
                with open(self._plan_path(gen - 1), "r", encoding="utf-8") as f:
                    prev = json.load(f)
                prev_size = len(prev["world"])
                seen = int(prev.get("scale_seq", 0))
            except (OSError, ValueError, KeyError):
                prev_size, seen = self._initial_world, 0
        req = self._read_scale()
        if req is not None and int(req.get("seq", 0)) > seen:
            seen = int(req["seq"])
            prev_size = int(req["to_world"])
        hi = self._ladder[-1] if self._ladder else len(avail)
        return max(1, min(prev_size, len(avail), hi)), seen

    def _form(self, gen: int) -> dict:
        """Join the generation-``gen`` barrier; return the agreed plan.

        Membership rule: wait until every member with a FRESH heartbeat
        has joined this generation — a slow-failing survivor keeps its
        heartbeat fresh, so the barrier waits for it; a dead member's
        heartbeat goes stale and it simply drops out of the set.  Gen 0
        additionally waits for the full launch-time membership (processes
        may still be starting, heartbeat-less).  The member with the
        lowest surviving tag is the leader: it allocates the coordinator
        port and publishes the plan; everyone else polls for it.

        Under ``--autoscale`` the plan splits the pool into an ACTIVE
        world (``world``, sized by :meth:`_target_world`) and parked
        ``standby`` members; without it the plan keeps its historical
        shape (world = everyone, no standby).
        """
        t_form0 = time.perf_counter()
        self._join(gen)
        deadline = time.monotonic() + FORM_TIMEOUT_SEC
        plan_path = self._plan_path(gen)
        while True:
            if os.path.exists(plan_path):
                break  # someone already published the plan
            if gen > 0 and self._done(gen - 1):
                # the previous generation completed while a scale/death
                # signal sent us here; nobody will ever form this one
                raise _PrevGenDone()
            fresh = self._fresh_members()
            fresh.add(self.tag)  # our own hb file may lag a beat
            joined = self._joined(gen)
            ready = (
                joined >= set(range(self.n_procs))
                if gen == 0
                else fresh <= joined
            )
            if ready:
                avail = sorted(joined & fresh | {self.tag})
                if avail and avail[0] == self.tag:
                    # re-elected coordinator: publish the formation plan
                    plan = {
                        "gen": gen,
                        "world": avail,
                        "coordinator": f"{self.coordinator_host}:{_free_port()}",
                    }
                    if self.autoscale is not None:
                        target, seen = self._target_world(gen, avail)
                        plan["world"] = avail[:target]
                        plan["standby"] = avail[target:]
                        plan["scale_seq"] = seen
                    _atomic_write_json(plan_path, plan)
                    break
                # not the leader: fall through and poll for the plan (if
                # the presumed leader died before writing, its heartbeat
                # goes stale and a later iteration elects the next tag)
            if time.monotonic() > deadline:
                raise FormationTimeout(
                    f"generation {gen} did not form within "
                    f"{FORM_TIMEOUT_SEC:.0f}s (joined={sorted(joined)}, "
                    f"fresh={sorted(fresh)})"
                )
            time.sleep(0.1)
        with open(plan_path, "r", encoding="utf-8") as f:
            plan = json.load(f)
        # the join-to-plan window of THIS member, on the merged timeline
        obs.complete(
            "elastic.form", t_form0, time.perf_counter(), cat="elastic",
            args={"gen": gen, "world": list(plan["world"])},
        )
        if (
            self.tag not in plan["world"]
            and self.tag not in plan.get("standby", [])
        ):
            # our heartbeat was stale when the plan was cut; aborting THIS
            # member is the safe outcome (the formed world runs without us)
            raise AnalysisError(
                f"member {self.tag} missed generation {gen} formation "
                f"(world={plan['world']}); aborting this launcher"
            )
        return plan

    # -- autoscale actuation ----------------------------------------------
    def _standby_wait(self, gen: int, plan: dict) -> str:
        """Park as a warm standby while generation ``gen`` runs without us.

        Returns ``"done"`` when the run completed (this member exits 0)
        or ``"next"`` when the generation ended another way — a scale
        request, a peer-marked failure, or an active member's heartbeat
        going stale — and the next formation needs us at the barrier.
        """
        obs.instant(
            "autoscale.standby", args={"gen": gen, "tag": self.tag}
        )
        scale_seq = int(plan.get("scale_seq", 0))
        active = set(plan["world"])
        while True:
            if self._done(gen):
                return "done"
            req = self._read_scale()
            if req is not None and int(req.get("seq", 0)) > scale_seq:
                return "next"
            if self._peer_failed(gen):
                return "next"
            if active - self._fresh_members():
                # an active member died outright; the survivors are
                # about to re-form and the barrier will want us fresh
                return "next"
            time.sleep(0.2)

    def _start_controller(self, gen: int, world: list[int], scale_seq: int):
        """Leader-only: per-generation policy controller (autoscale.py).

        Tails this member's own worker metrics shard — the leader IS
        rank 0, so that shard carries the ingest gauges of the rank that
        paces the collective step — and publishes at most one scale
        request into the rendezvous directory.  Returns None when the
        surviving world fell off the ladder (deaths below
        ``--autoscale-min``): scaling pauses until a formation puts the
        world back on a rung.
        """
        from .autoscale import AutoscaleController, append_decision_log

        a = self.autoscale
        if len(world) not in self._ladder:
            return None
        seq = scale_seq + 1

        def log(dec) -> None:
            # EVERY decision — actuated or observe-only (budget 0, the
            # rollout drill) — lands in the shared decision log, which
            # is what _patch_result folds into totals.autoscale
            append_decision_log(
                self._scale_log_path(), dec,
                gen=gen, seq_global=seq, t_wall=round(time.time(), 3),
            )

        def publish(dec) -> None:
            _atomic_write_json(self._scale_path(), {
                "seq": seq,
                "from_world": dec.from_world,
                "to_world": dec.to_world,
                "direction": dec.direction,
                "reason": dec.reason,
                "gen": gen,
                "t_wall": round(time.time(), 3),
            })

        ctrl = AutoscaleController(
            a,
            world=len(world),
            ladder=self._ladder,
            metrics_path=self._metrics_path(gen, self.tag),
            publish=publish,
            log=log,
            budget_left=max(0, a.reform_budget - scale_seq),
            cooldown_anchor=self._scale_anchor,
        )
        # scripted drills: entries already actuated by previous
        # generations' controllers must not re-fire
        ctrl.engine._plan_fired = min(scale_seq, len(ctrl.engine._plan))
        ctrl.start()
        return ctrl

    # -- child lifecycle --------------------------------------------------
    def _spawn_worker(self, gen: int) -> tuple[subprocess.Popen, object]:
        env = dict(os.environ)
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = os.pathsep.join(
            [pkg_root, *filter(None, [env.get("PYTHONPATH", "")])]
        )
        log = open(
            os.path.join(self._gen_dir(gen), f"worker-{self.tag}.log"), "ab"
        )
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ruleset_analysis_tpu.runtime.elastic",
                "worker",
                self.dir,
                str(self.tag),
                str(gen),
            ],
            env=env,
            stdout=log,
            stderr=subprocess.STDOUT,
        )
        return proc, log

    def _watch_worker(
        self,
        proc: subprocess.Popen,
        world: list[int],
        gen: int,
        *,
        scale_seq: int = 0,
        ctrl=None,
    ) -> int:
        """Wait for the worker; kill it when a peer is known lost.

        Two loss signals feed the same grace-then-kill path, making
        detection bounded on EVERY supported jax (where the installed jax
        has collective heartbeats those usually abort the survivors
        first; this watchdog is the version-proof bound):

        - a peer's rendezvous heartbeat went stale (whole-node death);
        - a peer marked this generation failed (worker-only death — its
          supervisor is alive and heartbeating, but our worker may be
          wedged in a collective that will never complete).

        A worker still running KILL_GRACE_SEC after either signal is
        presumed wedged and killed, which counts as an ordinary
        generation failure and feeds re-formation.
        """
        lost_since: float | None = None
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc
            if ctrl is not None and ctrl.error is not None:
                # the policy controller died (e.g. an injected
                # autoscale.decide fault): no scale request was ever
                # published, so the safe outcomes are continue-at-old-
                # world or typed abort — we abort typed, matching the
                # serve driver's semantics for the same seam
                proc.kill()
                proc.wait()
                err = ctrl.error
                if isinstance(err, AnalysisError):
                    raise err
                raise AnalysisError(f"autoscale controller failed: {err}") from err
            if self.autoscale is not None:
                req = self._read_scale()
                if (
                    req is not None
                    and int(req.get("seq", 0)) > scale_seq
                    and not self._done(gen)
                ):
                    # PLANNED retirement: kill the worker exactly like the
                    # certified death path — the next generation resumes
                    # from the epoch checkpoint (replaying at most
                    # checkpoint_every_chunks), report bit-identical.
                    # A generation already marked done is finishing: the
                    # request raced the final exits, and killing rank 0
                    # mid-report-write would lose the run — let the
                    # worker exit instead.
                    obs.instant(
                        "autoscale.retire",
                        args={"gen": gen, "tag": self.tag, **req},
                    )
                    proc.kill()
                    proc.wait()
                    self._scale_pending = {"t": time.monotonic(), **req}
                    return SCALE_RC
            peers = set(world) - {self.tag}
            stale = bool(peers - self._fresh_members())
            failed = self._peer_failed(gen)
            if stale or failed:
                if lost_since is None:
                    lost_since = time.monotonic()
                    self.meter.detect(
                        "peer heartbeat lost" if stale else "peer worker failed"
                    )
                elif time.monotonic() - lost_since > KILL_GRACE_SEC:
                    proc.kill()
                    proc.wait()
                    return -9
            else:
                # the lagging peer came back (load spike, not death): a
                # one-off stale reading must not arm a later kill
                lost_since = None
            time.sleep(0.2)

    # -- the supervised driver loop ---------------------------------------
    def run(self) -> tuple[int, str | None]:
        """Supervise until success or budget exhaustion.

        Returns ``(rc, result_json_path)``: rc 0 on success; the path is
        set only on the member whose worker held rank 0 of the final
        generation (the one that wrote the report).
        """
        os.makedirs(self._members_dir(), exist_ok=True)
        os.makedirs(self.epoch_dir, exist_ok=True)
        _atomic_write_json(
            os.path.join(self._members_dir(), f"{self.tag}.job.json"), self.job
        )
        self._hb = _Heartbeat(self._hb_path(self.tag))
        self._hb.start()
        # recovery totals ride every metrics snapshot while supervising
        # (satellite of the RecoveryMeter summary — an operator tailing
        # --metrics-out sees reforms_used move without waiting for the
        # final report)
        obs.register_sampler(
            "recovery",
            lambda: {"reforms_used": self.reforms_used, **self.meter.summary()},
        )
        try:
            gen = 0
            world: list[int] = []
            while True:
                try:
                    plan = self._form(gen)
                except _PrevGenDone:
                    # the run completed while a scale/death signal sent
                    # us to the next barrier.  If WE held rank 0 of the
                    # generation that completed, the report is ours to
                    # return — and it must exist intact: a planned
                    # retirement that raced the final report write must
                    # surface as a typed abort, never a silent exit 0
                    # with the report lost (the standing invariant)
                    out = self.job["out"]
                    if not (world and world[0] == self.tag and out):
                        return 0, None  # completed without us
                    path = out + ".json"
                    try:
                        with open(path, "r", encoding="utf-8") as f:
                            json.load(f)
                    except (OSError, ValueError) as e:
                        raise AnalysisError(
                            "elastic: run completed but rank 0's report "
                            f"at {path!r} is missing or torn (a scale/"
                            "death signal raced the final write); "
                            "re-run to regenerate it"
                        ) from e
                    return 0, self._patch_result(path)
                except FormationTimeout as e:
                    print(f"elastic: {e}", file=sys.stderr)
                    return exit_code_for(e), None  # stall class (6)
                world = list(plan["world"])
                scale_seq = int(plan.get("scale_seq", 0))
                if self.tag not in world:
                    # parked warm standby: heartbeat on, no worker — we
                    # join the next formation when a scale-out (or a
                    # death backfill) needs us
                    self._scale_pending = None
                    if self._standby_wait(gen, plan) == "done":
                        return 0, None
                    gen += 1
                    continue
                if self._scale_pending is not None:
                    # the planned scale event is applied: the new world
                    # formed and its worker is about to run
                    rec = {
                        "applied_seq": int(self._scale_pending.get("seq", scale_seq)),
                        "gen": gen,
                        "world": len(world),
                        "time_to_effect_sec": round(
                            time.monotonic() - self._scale_pending["t"], 3
                        ),
                    }
                    self._scale_anchor = time.monotonic()
                    if world[0] == self.tag:
                        with open(
                            self._scale_log_path(), "a", encoding="utf-8"
                        ) as f:
                            f.write(json.dumps(
                                {"kind": "applied", **rec},
                                separators=(",", ":"),
                            ) + "\n")
                    obs.metric_event("autoscale.applied", **rec)
                    self._scale_pending = None
                if gen > 0 and self.meter.detecting:
                    # the moment the replacement cluster is formed and its
                    # worker is about to run — the recovery is complete
                    # (planned scale re-formations have no detect window
                    # and must not pollute the MTTR statistics)
                    self.meter.recovered(world=len(world))
                proc, log = self._spawn_worker(gen)
                ctrl = None
                if self.autoscale is not None and world[0] == self.tag:
                    ctrl = self._start_controller(gen, world, scale_seq)
                try:
                    rc = self._watch_worker(
                        proc, world, gen, scale_seq=scale_seq, ctrl=ctrl
                    )
                finally:
                    log.close()
                    if ctrl is not None:
                        ctrl.stop()
                        ctrl.join(timeout=5.0)
                if rc == 0:
                    self.final_world = world
                    self._mark_done(gen)
                    out = self.job["out"]
                    if world[0] == self.tag and out:
                        return 0, self._patch_result(out + ".json")
                    return 0, None
                if rc == SCALE_RC:
                    req = self._scale_pending or {}
                    seq_seen = int(req.get("seq", scale_seq + 1))
                    print(
                        f"elastic: planned scale event #{seq_seen}: "
                        f"world {req.get('from_world')}->{req.get('to_world')} "
                        f"({req.get('reason', '?')}); re-forming",
                        file=sys.stderr,
                    )
                    # chaos seam: actuation failing between retiring the
                    # old world and forming the new one must be a typed
                    # abort over an intact epoch checkpoint, never a hang
                    faults.fire("autoscale.spawn")
                    gen += 1
                    continue
                if rc == DIE_RC:
                    # fault injection: this NODE is simulated dead — take
                    # the heartbeat down with us, abruptly
                    os._exit(DIE_RC)
                # tell the peers this generation is dead even though WE
                # are alive — their workers may be wedged in a collective
                # and their supervisors see our heartbeat as healthy (the
                # worker-only-death signal; see _watch_worker)
                self._mark_failed(gen)
                self.meter.detect(f"worker exited rc={rc}")
                self.reforms_used += 1
                if self.reforms_used > self.max_reforms:
                    self.meter.abandon()
                    print(
                        f"elastic: re-formation budget exhausted "
                        f"({self.reforms_used - 1} re-forms used, "
                        f"--max-reforms {self.max_reforms}); aborting "
                        f"(last worker rc={rc}, log: "
                        f"{self._gen_dir(gen)}/worker-{self.tag}.log)",
                        file=sys.stderr,
                    )
                    # documented failure-class exit code (errors.py):
                    # supervisors branch on 7 = ReformBudgetExhausted
                    return EXIT_REFORM_BUDGET, None
                print(
                    f"elastic: generation {gen} failed (worker rc={rc}); "
                    f"re-forming ({self.reforms_used}/{self.max_reforms})",
                    file=sys.stderr,
                )
                gen += 1
        finally:
            obs.unregister_sampler("recovery")
            if self._hb is not None:
                self._hb.stop()

    def _patch_result(self, result_path: str) -> str:
        """Fold the supervisor's recovery + autoscale totals into the report."""
        try:
            with open(result_path, "r", encoding="utf-8") as f:
                rep = json.load(f)
        except (OSError, ValueError):
            return result_path  # report stands as written
        rec = {"reforms_used": self.reforms_used, **self.meter.summary()}
        rep.setdefault("totals", {})["recovery"] = rec
        if self.autoscale is not None:
            from .autoscale import flap_count, read_decision_log

            log = read_decision_log(self._scale_log_path())
            decisions = [r for r in log if r.get("kind") != "applied"]
            applied = [r for r in log if r.get("kind") == "applied"]
            rep["totals"]["autoscale"] = {
                "scale_events": len(applied),
                "scale_out": sum(
                    1 for r in decisions if r.get("direction") == "out"
                ),
                "scale_in": sum(
                    1 for r in decisions if r.get("direction") == "in"
                ),
                "flaps": flap_count(
                    decisions,
                    cooldown_sec=self.autoscale.cooldown_sec,
                    sustain_sec=self.autoscale.sustain_sec,
                ),
                "final_world": len(self.final_world or []),
                "decisions": decisions,
                "applied": applied,
            }
        _atomic_write_json(result_path, rep)
        return result_path


# ---------------------------------------------------------------------------
# Worker (child) entry — one generation of actual analysis
# ---------------------------------------------------------------------------


def _start_supervisor_watchdog() -> None:
    """Abort this worker if its supervisor dies (per-generation liveness).

    The supervisor owns the heartbeat; if it dies, the peers re-form
    WITHOUT this member while its orphaned worker would keep computing
    and — worst case — keep writing epoch snapshots over the new
    generation's.  Reparenting (getppid change) is the cheap, version-
    proof orphan signal; exit is abrupt on purpose (the collectives this
    worker holds open must abort, not drain)."""
    ppid = os.getppid()

    def watch() -> None:
        while True:
            if os.getppid() != ppid:
                print(
                    "elastic worker: supervisor died (orphaned); aborting",
                    file=sys.stderr,
                    flush=True,
                )
                os._exit(1)
            time.sleep(1.0)

    threading.Thread(
        target=watch, daemon=True, name="ra-supervisor-watchdog"
    ).start()


def _worker_main(elastic_dir: str, tag: int, gen: int) -> int:
    # trace shard arming is inherited via RA_TRACE_DIR (supervisor env);
    # the label names this generation worker's track in the merged view.
    # The flight recorder arms the same way (RA_BLACKBOX_DIR, inside
    # note_role's lazy env check): a generation worker that dies typed
    # dumps its ring via the excepthook, and a clean generation seals at
    # exit so a later supervisor abort can still merge its telemetry.
    obs.note_role(f"elastic-worker-{tag}-gen{gen}")
    from . import flightrec

    flightrec.cursor(elastic_gen=gen, elastic_tag=tag)
    _start_supervisor_watchdog()
    with open(
        os.path.join(elastic_dir, "members", f"{tag}.job.json"),
        "r",
        encoding="utf-8",
    ) as f:
        job = json.load(f)
    with open(
        os.path.join(elastic_dir, f"gen-{gen}", "plan.json"),
        "r",
        encoding="utf-8",
    ) as f:
        plan = json.load(f)
    world = list(plan["world"])
    if tag not in world:
        print(f"worker {tag}: not in generation {gen} world {world}", file=sys.stderr)
        return 4
    rank, nproc = world.index(tag), len(world)

    from ..parallel.distributed import init_distributed
    from .compcache import enable_persistent_cache

    # every generation is a fresh process: without the on-disk cache each
    # re-formation would re-pay the full step compile, inflating
    # time-to-recover by the compile time
    enable_persistent_cache()
    init_distributed(
        plan["coordinator"],
        nproc,
        rank,
        heartbeat_timeout_seconds=job["heartbeat_timeout"],
        initialization_timeout=job["init_timeout"],
    )

    import numpy as np

    from ..config import AnalysisConfig
    from ..hostside import pack
    from . import checkpoint as ckpt
    from .stream import run_stream_file_distributed

    packed = pack.load_packed(job["ruleset"])
    cfg = AnalysisConfig.from_dict(job["cfg"])
    epoch_dir = os.path.join(elastic_dir, "epoch")
    snap = ckpt.load(epoch_dir)
    shards = list(job["shards"])
    man_shards, cursors, done = manifest_of(snap)
    if man_shards is not None and man_shards != shards:
        raise ckpt.CheckpointMismatch(
            f"epoch snapshot in {epoch_dir!r} covers different shards; "
            "refusing to merge"
        )
    acfg = job.get("autoscale")
    if acfg:
        # arm the metrics snapshotter on this worker's per-generation
        # shard: the leader supervisor's policy controller tails rank
        # 0's shard for the canonical backpressure/starvation signals
        # (autoscale.ingest_signals) — the SAME JSONL an operator's
        # --metrics-out would carry, one source of truth
        obs.start_metrics(
            os.path.join(elastic_dir, f"gen-{gen}", f"metrics-{tag}.jsonl"),
            every_sec=float(acfg.get("poll_sec", 0.5)),
        )
    try:
        pace = float(os.environ.get("RA_ELASTIC_PACE", "") or 0.0)
    except ValueError:
        pace = 0.0
    fault = job.get("fault")
    die = None
    if (
        fault is not None
        and int(fault["tag"]) == tag
        and (fault.get("gen") is None or gen == int(fault["gen"]))
    ):
        # no gen filter: the fault arms at this tag's FIRST opportunity
        # (its supervisor dies with it, so it never fires twice)
        die = int(fault["after_batches"])
    spec = ElasticRunSpec(
        epoch_dir=epoch_dir,
        shards=shards,
        assignments=assign_shards(shards, cursors, done, nproc)[rank],
        snapshot=snap,
        base_cursors=cursors,
        base_done=done,
        epoch=gen,
        die_after_batches=die,
        pace_sec=pace,
    )
    try:
        report, regs = run_stream_file_distributed(
            packed,
            [],
            cfg,
            native=job["native"],
            topk=job["topk"],
            return_state=True,
            elastic=spec,
        )
    finally:
        flightrec.seal()
    if rank == 0 and job["out"]:
        np.savez(job["out"] + ".npz", **regs)
        _atomic_write_json(job["out"] + ".json", report.to_json())
    print(f"worker {tag} (rank {rank}/{nproc}, gen {gen}) done", file=sys.stderr)
    return 0



if __name__ == "__main__":
    if len(sys.argv) == 5 and sys.argv[1] == "worker":
        raise SystemExit(
            _worker_main(sys.argv[2], int(sys.argv[3]), int(sys.argv[4]))
        )
    print(
        "usage: python -m ruleset_analysis_tpu.runtime.elastic worker "
        "ELASTIC_DIR TAG GEN  (spawned by ElasticSupervisor)",
        file=sys.stderr,
    )
    raise SystemExit(2)
