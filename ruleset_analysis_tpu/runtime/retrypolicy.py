"""Typed retry/backoff engine: the transient-fault survival tier.

The chaos contract through PR 13 was "bit-identical report or typed
abort, never a wrong answer" — but a typed abort is still an outage,
and the faults that caused most of them are *transient*: a flaky
``device_put``, a torn checkpoint fsync, a listener socket in TIME_WAIT,
a publisher hitting a momentarily-full disk.  This module is the one
place retry behavior lives (DESIGN §19):

- **Sites.**  :data:`RETRY_SITES` registers every seam the runtime
  wraps with :func:`call` — host->device transfer, the checkpoint
  write+fsync phase, wire/manifest read IO, listener bind and receive
  loops, serve report publication.  Each entry names the ``faults.py``
  site that exercises it, so the chaos harness and the registry auditor
  (verify/registry.py::audit_retry) can prove every seam has a policy
  entry, a transient schedule, and a permanent-escalation test.

- **Policies.**  A :class:`RetryPolicy` bounds each site: attempt count,
  exponential backoff with a cap, and a per-site per-run retry *budget*
  (a seam that keeps failing must eventually escalate even across
  calls).  Overrides arm from ``AnalysisConfig.retry_policy``
  (``run/serve --retry-policy "site=attempts/base_sec,...,seed=S"``;
  ``"off"`` collapses every site to a single attempt for A/B
  measurement).

- **Determinism.**  Backoff jitter derives from
  ``crc32(seed | site | attempt)``, not a process RNG — the same seed
  produces the same delay sequence in every process
  (:func:`backoff_schedule`; property-tested across interpreters), so a
  chaos replay is bit-reproducible including its timing decisions.

- **Classification.**  Only faults ``errors.is_transient`` accepts are
  retried.  ``InjectedFault`` is transient by definition (it is the
  chaos stand-in for exactly these environmental faults); every other
  typed ``AnalysisError`` is a deliberate refusal and escalates
  immediately, so the existing typed-abort invariant is unchanged —
  an exhausted budget re-raises the last underlying error.

- **Observability.**  Every retry emits a ``retry.attempt`` obs instant
  (flushed BEFORE the sleep, so a crash mid-backoff still shows the
  decision), recoveries and giveups emit their own instants, and
  :func:`counters`/:func:`gauges` feed the metrics JSONL and the serve
  ``/metrics`` endpoint (JSON + prom).  ``tools/trace_summary.py``
  renders the retries block from the instants alone.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import zlib

from ..errors import AnalysisError, is_transient


@dataclasses.dataclass(frozen=True)
class RetrySite:
    """One registered retryable seam."""

    #: the runtime/faults.py site whose ``site@N:k`` transient schedules
    #: exercise this seam (audit_retry joins the two registries on it)
    fault_site: str
    description: str


#: Registered retry sites.  Adding a seam here without a policy entry, a
#: ``retrypolicy.call`` site, a transient chaos schedule, and a
#: permanent-escalation test fails ``make lint`` (audit_retry).
RETRY_SITES: dict[str, RetrySite] = {
    "device_put": RetrySite(
        "stream.device_put.fail",
        "host->device transfer (mesh.shard_batch/shard_grouped/"
        "shard_ring_batch); a transient XLA runtime fault must not kill "
        "a run holding hours of register state",
    ),
    "checkpoint.save": RetrySite(
        "checkpoint.torn_state",
        "the checkpoint write+fsync phase (state npz + manifest into the "
        "tmp dir); absorbs the former ad-hoc snap-name collision loop so "
        "its attempts are one configurable, observable knob",
    ),
    "wire.read": RetrySite(
        "stream.wire.read.fail",
        "wire-file and convert-manifest open/header read IO; a cold-NFS "
        "hiccup at open time must not abort a resumable run",
    ),
    "listener.bind": RetrySite(
        "listener.bind.fail",
        "serve listener socket bind (TIME_WAIT rebind after a restart is "
        "the canonical transient)",
    ),
    "listener.accept": RetrySite(
        "listener.accept.fail",
        "a serve listener's receive loop; a transient socket fault "
        "re-enters the loop instead of killing the listener (a dead "
        "listener marks every overlapping window incomplete)",
    ),
    "serve.publish": RetrySite(
        "serve.publish.fail",
        "serve report publication to disk; exhaustion degrades the "
        "publisher subsystem (in-memory endpoints keep serving) rather "
        "than aborting ingest",
    ),
    "dist.epoch.ship": RetrySite(
        "dist.epoch.ship",
        "shipping a window epoch from an ingest host to the merge "
        "supervisor; a transient socket fault retries in place, "
        "exhaustion enters partition mode (the epoch waits in the "
        "backlog, the durable spool already holds it) instead of "
        "killing the host's ingest tier",
    ),
}


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounds for one site: attempts per call, backoff, per-run budget."""

    attempts: int = 5  # total tries per call (1 = no retry)
    base_sec: float = 0.1  # first backoff delay
    mult: float = 2.0  # exponential growth per retry
    cap_sec: float = 2.0  # ceiling on any single delay
    budget: int = 64  # retries allowed per site per run (across calls)

    def __post_init__(self) -> None:
        if self.attempts < 1:
            raise AnalysisError(f"retry attempts must be >= 1, got {self.attempts}")
        if self.base_sec < 0 or self.cap_sec < 0 or self.mult < 1.0:
            raise AnalysisError(
                "retry backoff needs base_sec/cap_sec >= 0 and mult >= 1"
            )
        if self.budget < 0:
            raise AnalysisError(f"retry budget must be >= 0, got {self.budget}")


#: Default policy table.  Per-site deviations are deliberate: the bind
#: seam waits out TIME_WAIT (longer base), the device seam spins fast
#: (the transfer either clears in milliseconds or the runtime is gone).
DEFAULT_POLICIES: dict[str, RetryPolicy] = {
    "device_put": RetryPolicy(attempts=5, base_sec=0.05, cap_sec=1.0),
    "checkpoint.save": RetryPolicy(attempts=5, base_sec=0.1, cap_sec=2.0),
    "wire.read": RetryPolicy(attempts=4, base_sec=0.1, cap_sec=2.0),
    "listener.bind": RetryPolicy(attempts=6, base_sec=0.2, cap_sec=2.0),
    "listener.accept": RetryPolicy(attempts=5, base_sec=0.1, cap_sec=2.0),
    "serve.publish": RetryPolicy(attempts=4, base_sec=0.05, cap_sec=1.0),
    # the ship seam spins fast and gives up early: the durable spool
    # already holds the epoch, so a persistent failure should enter
    # partition mode (heal-time reconciliation) quickly, not block the
    # host's serve loop through a long backoff ladder
    "dist.epoch.ship": RetryPolicy(attempts=4, base_sec=0.05, cap_sec=0.5),
}

assert set(DEFAULT_POLICIES) == set(RETRY_SITES)


class _SiteCounters:
    __slots__ = ("attempts", "recoveries", "giveups", "budget_spent")

    def __init__(self):
        self.attempts = 0  # retries issued (first tries are not counted)
        self.recoveries = 0  # calls that succeeded after >= 1 retry
        self.giveups = 0  # calls that escalated (exhausted or permanent)
        self.budget_spent = 0  # retries charged against the per-run budget


_lock = threading.Lock()
_policies: dict[str, RetryPolicy] = dict(DEFAULT_POLICIES)
_seed = 0
_counters: dict[str, _SiteCounters] = {}

#: Environment override for bare library calls (the CLI/driver spec via
#: ``AnalysisConfig.retry_policy`` wins when both are set).
ENV_VAR = "RA_RETRY_POLICY"
_env_checked = False


def parse_spec(spec: str) -> tuple[dict[str, RetryPolicy], int]:
    """``"site=attempts/base,...,seed=S"`` | ``"off"`` -> (overrides, seed).

    ``off`` maps every site to a single attempt (retries disabled; the
    bench's disarmed-overhead A/B and incident triage both use it).
    ``site=attempts`` keeps the site's default backoff; ``/base_sec``
    overrides the first delay too.
    """
    overrides: dict[str, RetryPolicy] = {}
    seed = 0
    if spec.strip() == "off":
        return (
            {s: dataclasses.replace(p, attempts=1)
             for s, p in DEFAULT_POLICIES.items()},
            0,
        )
    for part in filter(None, (p.strip() for p in spec.split(","))):
        if part.startswith("seed="):
            try:
                seed = int(part[5:])
            except ValueError as e:
                raise AnalysisError(f"bad retry-policy seed {part!r}") from e
            continue
        site, eq, rest = part.partition("=")
        if not eq or site not in RETRY_SITES:
            raise AnalysisError(
                f"bad retry-policy entry {part!r}; registered sites: "
                f"{', '.join(sorted(RETRY_SITES))} (want "
                "site=attempts[/base_sec] or seed=S or 'off')"
            )
        attempts_s, slash, base_s = rest.partition("/")
        try:
            attempts = int(attempts_s)
            base = float(base_s) if slash else DEFAULT_POLICIES[site].base_sec
        except ValueError as e:
            raise AnalysisError(
                f"bad retry-policy entry {part!r} (want site=attempts[/base_sec])"
            ) from e
        overrides[site] = dataclasses.replace(
            DEFAULT_POLICIES[site], attempts=attempts, base_sec=base
        )
    return overrides, seed


def configure(spec: str = "") -> None:
    """Arm the policy table for this run; counters reset.

    Idempotent per spec string so drivers may call it unconditionally at
    run start (the ``faults.arm_spec`` discipline).  An empty spec means
    the defaults plus any :data:`ENV_VAR` override.
    """
    global _policies, _seed, _env_checked
    if not spec:
        spec = os.environ.get(ENV_VAR, "")
    overrides, seed = parse_spec(spec) if spec else ({}, 0)
    with _lock:
        _policies = {**DEFAULT_POLICIES, **overrides}
        _seed = seed
        _counters.clear()
        _env_checked = True
    # live gauges for the metrics JSONL whenever a plane is armed
    from . import obs

    obs.register_sampler("retry", counters)


def policy(site: str) -> RetryPolicy:
    try:
        return _policies[site]
    except KeyError:
        raise AnalysisError(
            f"unregistered retry site {site!r}; registered: "
            f"{', '.join(sorted(RETRY_SITES))}"
        ) from None


def _jitter_frac(seed: int, site: str, attempt: int) -> float:
    """Deterministic jitter in [0, 1): crc32 of (seed, site, attempt).

    zlib.crc32, not hash(): identical across processes regardless of
    PYTHONHASHSEED — the property test spawns an interpreter to prove it.
    """
    return zlib.crc32(f"{seed}|{site}|{attempt}".encode()) / 2**32


def backoff_delay(site: str, attempt: int, seed: int | None = None) -> float:
    """Delay before retry ``attempt`` (1-based) at ``site``, in seconds.

    ``min(cap, base * mult**(attempt-1)) * (0.5 + jitter)`` — full
    exponential shape, +/-50% deterministic spread so a fleet of
    retriers with distinct seeds never thunders in phase.
    """
    pol = policy(site)
    s = _seed if seed is None else seed
    raw = min(pol.cap_sec, pol.base_sec * pol.mult ** (attempt - 1))
    return raw * (0.5 + _jitter_frac(s, site, attempt))


def backoff_schedule(site: str, n: int, seed: int = 0) -> list[float]:
    """The first ``n`` delays for ``site`` under ``seed`` (pure; tests)."""
    return [round(backoff_delay(site, a, seed), 9) for a in range(1, n + 1)]


def _site_counters(site: str) -> _SiteCounters:
    c = _counters.get(site)
    if c is None:
        with _lock:
            c = _counters.setdefault(site, _SiteCounters())
    return c


def _sleep(delay: float, stop: threading.Event | None) -> None:
    if stop is not None:
        stop.wait(delay)
    elif delay > 0:
        time.sleep(delay)


def call(site: str, fn, *, stop: threading.Event | None = None):
    """Run ``fn()`` under ``site``'s policy; the one retry entry point.

    Transient failures (errors.is_transient) retry with seeded backoff
    until the per-call attempt bound or the per-run site budget runs
    out; the final failure — and every permanent one — re-raises the
    ORIGINAL exception, so exhausted budgets escalate to exactly the
    typed aborts the chaos invariant already covers.  ``stop`` makes the
    backoff sleep responsive to a shutting-down stage.
    """
    pol = policy(site)
    ctr = _site_counters(site)
    attempt = 1
    while True:
        try:
            out = fn()
        except BaseException as e:
            retryable = (
                is_transient(e)
                and attempt < pol.attempts
                and ctr.budget_spent < pol.budget
                and not (stop is not None and stop.is_set())
            )
            if not retryable:
                with _lock:
                    ctr.giveups += 1
                from . import obs

                obs.instant("retry.giveup", args={
                    "site": site, "attempt": attempt,
                    "error": type(e).__name__,
                    "transient": is_transient(e),
                })
                raise
            delay = backoff_delay(site, attempt)
            with _lock:
                ctr.attempts += 1
                ctr.budget_spent += 1
            from . import obs

            # flushed BEFORE the sleep: a crash mid-backoff still shows
            # the retry decision on the merged timeline
            obs.instant("retry.attempt", args={
                "site": site, "attempt": attempt,
                "delay_sec": round(delay, 4), "error": type(e).__name__,
            })
            _sleep(delay, stop)
            attempt += 1
            continue
        if attempt > 1:
            with _lock:
                ctr.recoveries += 1
            from . import obs

            obs.instant("retry.recovered", args={
                "site": site, "attempts": attempt,
            })
        return out


def counters() -> dict:
    """Per-site attempt/recovery/giveup counts (metrics JSONL sampler)."""
    with _lock:
        return {
            site: {
                "attempts": c.attempts,
                "recoveries": c.recoveries,
                "giveups": c.giveups,
            }
            for site, c in sorted(_counters.items())
        }


def gauges(prefix: str = "retry_") -> dict:
    """Flat numeric gauges for serve ``/metrics`` (JSON + prom render)."""
    out: dict[str, int] = {
        f"{prefix}attempts_total": 0,
        f"{prefix}recoveries_total": 0,
        f"{prefix}giveups_total": 0,
    }
    with _lock:
        items = list(_counters.items())
    for site, c in items:
        key = site.replace(".", "_")
        out[f"{prefix}attempts_total"] += c.attempts
        out[f"{prefix}recoveries_total"] += c.recoveries
        out[f"{prefix}giveups_total"] += c.giveups
        out[f"{prefix}{key}_attempts"] = c.attempts
        out[f"{prefix}{key}_recoveries"] = c.recoveries
        out[f"{prefix}{key}_giveups"] = c.giveups
    return out


def _reset_for_tests() -> None:
    global _policies, _seed
    with _lock:
        _policies = dict(DEFAULT_POLICIES)
        _seed = 0
        _counters.clear()
