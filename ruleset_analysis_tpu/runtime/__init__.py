"""Runtime: streaming driver, checkpointing, reporting, metrics."""
