"""Checkpoint/resume: (stream offset, register files) snapshots.

The reference has no checkpointing — a failed Hadoop job reruns from
scratch, with YARN re-executing failed tasks (SURVEY.md §6).  The rebuild
does better with almost no machinery *because the state is mergeable*:
a snapshot is the exact analysis of lines ``[0, offset)``, so resume =
load registers + skip ``offset`` raw lines + keep streaming.  No replay
log, no partial-output reconciliation; killing a run between (or during)
chunks and resuming yields bit-identical final registers.

Format: a versioned snapshot directory (``snap-<n>/`` holding the register
``.npz`` plus a ``.json`` manifest with offset, chunk count, packer
counters, top-K tracker tables, and a config/ruleset fingerprint that
refuses resumes against a different ruleset or sketch geometry), published
by atomically renaming a ``LATEST`` pointer file.  A crash at ANY point of
a save — including between writing the snapshot files — leaves the
previous pointer (and therefore a consistent offset/register pair) intact;
superseded snapshot dirs are pruned only after the pointer moves.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
import time
import zipfile
import zlib

import numpy as np

from ..config import AnalysisConfig
# re-exports: raised on foreign / undecodable snapshots
from ..errors import CheckpointCorrupt, CheckpointMismatch
from ..hostside.pack import PackedRuleset
from ..ops.topk import TopKTracker

__all__ = [
    "CheckpointCorrupt",
    "CheckpointMismatch",
    "Snapshot",
    "fingerprint",
    "load",
    "restore_tracker",
    "save",
]

STATE_FILE = "state.npz"
MANIFEST_FILE = "manifest.json"
POINTER_FILE = "LATEST"


def fingerprint(
    packed: PackedRuleset, cfg: AnalysisConfig, n_shards: int = 1, lane: int = 0
) -> str:
    """Identity of (ruleset, sketch geometry, chunking) a snapshot is valid for.

    ``n_shards`` is the data-axis size of the mesh the stream actually runs
    on: both the padded chunk size and the per-chunk candidate count scale
    with it, so resuming on a different device count must be refused to
    keep talker tables bit-identical to an uninterrupted run.  ``lane`` is
    the resolved per-ACL lane width when the stream runs the stacked
    layout (0 for flat) — layouts must not cross-resume.

    Elastic tiers pin a LADDER MAXIMUM here, never the live world size:
    the elastic batch plane passes its world-ladder max (runtime/
    elastic.py), and the distributed serve tier passes its host-ladder
    max (``DistServeConfig.ladder_max``, runtime/distserve.py) — merged
    registers are world-size-independent under the merge laws, so a
    snapshot taken at any rung must resume at any other rung of the
    SAME ladder.  What must still be refused is a changed ceiling:
    resizing the ladder itself re-partitions what the fingerprint's
    geometry terms mean, so it is part of the resume identity.
    """
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(packed.rules).tobytes())
    if packed.has_v6:
        # pure-v4 rulesets hash exactly as before the v6 data model, so
        # pre-v6 snapshots of pure-v4 runs stay resumable
        h.update(np.ascontiguousarray(packed.rules6).tobytes())
    h.update(np.ascontiguousarray(packed.deny_key).tobytes())
    s = cfg.sketch
    padded = ((cfg.batch_size + n_shards - 1) // n_shards) * n_shards
    h.update(
        f"{s.cms_width},{s.cms_depth},{s.talk_cms_depth},{s.hll_p},{cfg.exact_counts},"
        f"{padded},{n_shards},{s.topk_chunk_candidates},{s.topk_capacity},"
        f"{cfg.layout},{lane},{s.topk_sample_shift}".encode()
    )
    if s.topk_every != 1:
        # deferred selection changes WHICH chunks feed candidates, so a
        # cross-cadence resume would not replay an uninterrupted run's
        # talker tables.  Folded in only when non-default so every
        # pre-existing snapshot keeps its fingerprint.  update_impl is
        # deliberately NOT part of the identity: scatter and sorted are
        # bit-identical, so a crash under one may resume under the other.
        h.update(f",topk_every={s.topk_every}".encode())
    return h.hexdigest()[:16]


def fence_fingerprint(base: str, term: int) -> str:
    """Stamp a distributed-serve fencing term onto a base fingerprint.

    The term is NOT part of the resume identity — a failover successor
    (term N+1) must restore its dead predecessor's snapshot (term N) —
    so it rides as a ``-t<term>`` suffix that ``split_fence`` peels off
    before the strict base comparison.  What the suffix buys is fencing
    at the storage layer: a restore that finds a snapshot from a HIGHER
    term than the restoring supervisor's lease proves a successor
    already ran, and the stale supervisor must abort typed
    (SupervisorFenced) instead of republishing old windows (DESIGN §23).
    """
    return f"{base}-t{term}"


def split_fence(fp: str) -> tuple[str, int]:
    """Split a fingerprint into (base, fencing term).

    Fingerprints without a ``-t<term>`` suffix (every pre-failover
    snapshot, and every non-distserve snapshot) split as term 0 so old
    snapshots keep restoring unchanged.
    """
    base, sep, tail = fp.rpartition("-t")
    if sep and tail.isdigit():
        return base, int(tail)
    return fp, 0


@dataclasses.dataclass
class Snapshot:
    """Host-side image of one checkpoint."""

    arrays: dict[str, np.ndarray]  # AnalysisState fields
    lines_consumed: int  # raw lines taken from the input iterator
    n_chunks: int
    parsed: int
    skipped: int
    tracker_tables: dict[int, dict[int, int]]
    fingerprint: str
    #: Schema extension point (JSON-serializable).  The elastic subsystem
    #: stores its epoch tag and world-size-independent per-shard cursor
    #: manifest here (runtime/elastic.py); plain per-process snapshots
    #: leave it None, and old snapshots load with None — the base schema
    #: is unchanged either way.
    extra: dict | None = None


def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(block, crc)


def _manifest_crc32(manifest: dict) -> int:
    """CRC of the manifest's canonical JSON, excluding the crc field itself.

    Covers every field a bit-flip could silently skew — offsets, packer
    counters, tracker tables, and the elastic per-shard cursor manifest
    in ``extra`` (a flipped cursor digit decodes as perfectly valid JSON
    and would resume from the wrong line without this).
    """
    body = {k: v for k, v in manifest.items() if k != "crc32"}
    return zlib.crc32(
        json.dumps(body, sort_keys=True, separators=(",", ":")).encode()
    ) & 0xFFFFFFFF


def save(ckpt_dir: str, snap: Snapshot) -> None:
    from . import faults, obs, retrypolicy

    t_save0 = time.perf_counter()
    os.makedirs(ckpt_dir, exist_ok=True)

    # The whole write+fsync phase runs under the central checkpoint.save
    # retry policy: a transient IO fault (torn write, EIO, a momentary
    # ENOSPC) re-attempts into a FRESH tmp dir — the failed attempt is
    # removed so retries never leak .tmp- litter — and a persistent one
    # escalates the original typed error after the policy's bounded
    # attempts (this absorbs the pre-PR-14 ad-hoc retry loop: attempts
    # and backoff are now one configurable, observable knob).
    def _write_tmp() -> str:
        tmp_dir = tempfile.mkdtemp(dir=ckpt_dir, prefix=".tmp-")
        try:
            state_path = os.path.join(tmp_dir, STATE_FILE)
            with open(state_path, "wb") as f:
                np.savez(f, **snap.arrays)
                f.flush()
                os.fsync(f.fileno())
            # fault site: crash leaving a half-written register file —
            # the pointer never moves, so load() keeps the prior epoch
            faults.fire("checkpoint.torn_state", path=state_path)
            manifest = {
                "lines_consumed": snap.lines_consumed,
                "n_chunks": snap.n_chunks,
                "parsed": snap.parsed,
                "skipped": snap.skipped,
                "fingerprint": snap.fingerprint,
                "tracker": [
                    [acl, list(table.items())]
                    for acl, table in snap.tracker_tables.items()
                ],
                # integrity: npz CRC + manifest self-CRC, verified on load
                "state_crc32": _file_crc32(state_path),
            }
            if snap.extra is not None:
                manifest["extra"] = snap.extra
            manifest["crc32"] = _manifest_crc32(manifest)
            manifest_path = os.path.join(tmp_dir, MANIFEST_FILE)
            with open(manifest_path, "w", encoding="utf-8") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            faults.fire("checkpoint.torn_manifest", path=manifest_path)
            # Snapshot data and its directory entries must be durable
            # BEFORE the pointer moves, or a power loss could persist a
            # pointer to truncated files (the small rename often hits
            # disk first).
            _fsync_dir(tmp_dir)
        except BaseException:
            _rmtree(tmp_dir)
            raise
        return tmp_dir

    tmp_dir = retrypolicy.call("checkpoint.save", _write_tmp)
    # Never delete an existing dir (LATEST may point at it): a same-chunk
    # re-save lands under a fresh name and the old one is pruned only
    # after the pointer moves.  Bounded by the same policy's attempt
    # count — a directory that keeps colliding past it is storage gone
    # mad, not a name race.
    snap_name = f"snap-{snap.n_chunks}"
    snap_dir = os.path.join(ckpt_dir, snap_name)
    for retry in range(1, retrypolicy.policy("checkpoint.save").attempts + 1):
        if not os.path.exists(snap_dir):
            break
        snap_name = f"snap-{snap.n_chunks}-r{retry}"
        snap_dir = os.path.join(ckpt_dir, snap_name)
    else:
        _rmtree(tmp_dir)
        raise CheckpointCorrupt(
            f"cannot find a free snapshot name for chunk {snap.n_chunks} "
            f"in {ckpt_dir!r} (storage litter?); clean the checkpoint dir"
        )
    os.replace(tmp_dir, snap_dir)
    _fsync_dir(ckpt_dir)
    # publish: the pointer rename is the commit point
    fd, tmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".ptr.tmp")
    with os.fdopen(fd, "w") as f:
        f.write(snap_name)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(ckpt_dir, POINTER_FILE))
    _fsync_dir(ckpt_dir)
    # the pointer rename above IS the commit point; mark it on the
    # timeline and push bytes/latency to the metrics JSONL (the size
    # stat only when a plane is armed — disarmed saves stay syscall-free
    # past their None-checks)
    if obs.active_tracer() is not None or obs.metrics_active():
        t_save1 = time.perf_counter()
        state_bytes = os.path.getsize(os.path.join(snap_dir, STATE_FILE))
        obs.complete(
            "checkpoint.save", t_save0, t_save1, cat="checkpoint",
            args={"n_chunks": snap.n_chunks, "bytes": int(state_bytes)},
        )
        obs.instant("checkpoint.commit", args={"snap": snap_name})
        obs.metric_event(
            "checkpoint",
            n_chunks=snap.n_chunks,
            lines_consumed=snap.lines_consumed,
            bytes=int(state_bytes),
            save_sec=round(t_save1 - t_save0, 4),
        )
    # Prune everything the new pointer does not reference — superseded
    # snapshots, orphans from a crash between snapshot rename and pointer
    # commit, and stale tmp dirs/files — only after the pointer is durable.
    for entry in os.listdir(ckpt_dir):
        if entry in (snap_name, POINTER_FILE):
            continue
        p = os.path.join(ckpt_dir, entry)
        if entry.startswith("snap-") or entry.startswith(".tmp-"):
            _rmtree(p)
        elif entry.endswith(".ptr.tmp"):
            try:
                os.unlink(p)
            except OSError:
                pass


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _read_pointer(ckpt_dir: str) -> str | None:
    try:
        with open(os.path.join(ckpt_dir, POINTER_FILE), "r", encoding="utf-8") as f:
            return f.read().strip()
    except FileNotFoundError:
        return None  # no checkpoint was ever committed here
    except NotADirectoryError:
        return None  # ckpt_dir path component is a file: nothing saved here
    except UnicodeDecodeError as e:
        # a pointer file holding non-UTF-8 bytes is storage corruption,
        # not a missing checkpoint — refuse loudly (a None here would
        # silently restart the analysis from scratch)
        raise CheckpointCorrupt(
            f"checkpoint pointer {os.path.join(ckpt_dir, POINTER_FILE)!r} "
            f"is corrupt ({e}); delete the checkpoint dir (or repair "
            "storage) to proceed"
        ) from e


def _rmtree(path: str) -> None:
    import shutil

    shutil.rmtree(path, ignore_errors=True)


def load(ckpt_dir: str) -> Snapshot | None:
    from . import obs

    with obs.span("checkpoint.load", dir=ckpt_dir):
        return _load(ckpt_dir)


def _load(ckpt_dir: str) -> Snapshot | None:
    name = _read_pointer(ckpt_dir)
    if name is None:
        return None  # no pointer file at all: genuinely no checkpoint
    snap_dir = os.path.join(ckpt_dir, name)
    state_path = os.path.join(snap_dir, STATE_FILE)
    manifest_path = os.path.join(snap_dir, MANIFEST_FILE)
    if not name or not (
        os.path.exists(state_path) and os.path.exists(manifest_path)
    ):
        # save() makes the snapshot dir durable BEFORE the pointer moves,
        # so a committed pointer that is empty or names a missing/partial
        # snapshot is storage corruption — refuse loudly rather than
        # silently starting the analysis from scratch (the most common
        # single-byte pointer flip stays valid UTF-8 and lands here)
        raise CheckpointCorrupt(
            f"checkpoint pointer in {ckpt_dir!r} names "
            f"{name!r} but no complete snapshot exists there; delete the "
            "checkpoint dir (or repair storage) to proceed"
        )
    try:
        with open(manifest_path, "r", encoding="utf-8") as f:
            m = json.load(f)
        # CRC verification (pre-CRC snapshots carry no fields and load
        # as before): the manifest self-CRC catches flips that decode as
        # valid JSON — a skewed offset or elastic cursor — and the state
        # CRC catches npz damage zipfile's per-member check can miss
        # (container metadata, whole-member substitution).
        if "crc32" in m and int(m["crc32"]) != _manifest_crc32(m):
            raise ValueError("manifest CRC32 mismatch (bit rot?)")
        if "state_crc32" in m and int(m["state_crc32"]) != _file_crc32(state_path):
            raise ValueError("register payload CRC32 mismatch (bit rot?)")
        with np.load(state_path) as z:
            arrays = {k: z[k] for k in z.files}
        return Snapshot(
            arrays=arrays,
            lines_consumed=int(m["lines_consumed"]),
            n_chunks=int(m["n_chunks"]),
            parsed=int(m["parsed"]),
            skipped=int(m["skipped"]),
            tracker_tables={
                int(acl): {int(k): int(v) for k, v in items}
                for acl, items in m["tracker"]
            },
            fingerprint=m["fingerprint"],
            extra=m.get("extra"),
        )
    except (
        ValueError,  # json.JSONDecodeError, np.load format errors
        KeyError,  # manifest/npz missing fields
        TypeError,  # reshaped manifest values
        OSError,  # short reads
        UnicodeDecodeError,
        zipfile.BadZipFile,  # npz container corrupt (plain Exception!)
    ) as e:
        raise CheckpointCorrupt(
            f"snapshot {snap_dir!r} is corrupt ({type(e).__name__}: "
            f"{str(e)[:200]}); delete it (or repair storage) to proceed"
        ) from e


def snapshot_of(
    state,
    *,
    lines_consumed: int,
    n_chunks: int,
    parsed: int,
    skipped: int,
    tracker: TopKTracker,
    fingerprint: str,
    extra: dict | None = None,
) -> Snapshot:
    """Host-side Snapshot of a device AnalysisState (fetches registers)."""
    from ..models.pipeline import state_to_host

    return Snapshot(
        arrays=state_to_host(state),
        lines_consumed=lines_consumed,
        n_chunks=n_chunks,
        parsed=parsed,
        skipped=skipped,
        tracker_tables=tracker.tables(),
        fingerprint=fingerprint,
        extra=extra,
    )


def state_of(snap: Snapshot, put_leaf):
    """Device AnalysisState from a Snapshot; ``put_leaf`` places each
    register (device_put for single-process, a global-array constructor
    for multi-process)."""
    from ..models.pipeline import AnalysisState

    return AnalysisState(**{k: put_leaf(v) for k, v in snap.arrays.items()})


def restore_tracker(snap: Snapshot, capacity: int) -> TopKTracker:
    t = TopKTracker(capacity)
    for acl, table in snap.tracker_tables.items():
        for src, est in table.items():
            t.offer(acl, src, est)
    return t
