"""The unused-rule report — the reference's L5 layer (SURVEY.md §2, §4.5).

Reference semantics: set-difference of all configured rules minus rules with
hits, ordered per ACL; plus per-rule hit counts.  The TPU rebuild adds the
sketched statistics (estimated counts, per-rule unique-source cardinality,
top talkers) to the same report structure.

Pure host code; consumes plain dicts so both the oracle backend and the TPU
backend feed it.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

from ..errors import AnalysisError
from ..hostside.oracle import RuleKey
from ..hostside.pack import PackedRuleset

#: ``totals`` keys that are wall-clock/process observations rather than
#: answers — the keys every report-identity test strips before comparing
#: runs bit-for-bit.  ONE list (tests import it; keeping a private copy
#: in a test module is a registry-auditor finding, verify/registry.py)
#: so a new volatile block added to the runtime cannot silently break
#: only SOME identity suites:
#:
#:   elapsed_sec/lines_per_sec/compile_sec/sustained_lines_per_sec —
#:       timings of this particular run
#:   ingest      pipeline overlap accounting (queue depths, waits)
#:   throughput  the meter's cumulative split timings
#:   coalesce    raw/unique compaction accounting (traffic-order shaped)
#:   autoscale   scale decisions/timings (wall-clock, not answers)
#:   recovery    elastic re-formation accounting
#:   devprof     capture-window timings, not answers
#:   lineage     provenance (term/path/publish stamp vary across
#:               control-vs-failover republication; its CORE fields
#:               have their own identity law — lineage_core below)
VOLATILE_TOTALS = (
    "elapsed_sec",
    "lines_per_sec",
    "compile_sec",
    "sustained_lines_per_sec",
    "ingest",
    "throughput",
    "coalesce",
    "autoscale",
    "recovery",
    "devprof",
    "degraded",
    "latency",
    "lineage",
)


@dataclasses.dataclass
class Report:
    """One analysis run's full output."""

    per_rule: list[dict]  # one entry per key, config order
    unused: list[RuleKey]
    totals: dict
    talkers: dict  # "<fw> <acl>" -> [[src_ip_str, count], ...]

    def to_json(self) -> str:
        return json.dumps(
            {
                "totals": self.totals,
                "per_rule": self.per_rule,
                "unused": [list(k) for k in self.unused],
                "talkers": self.talkers,
            },
            indent=2,
        )

    def to_text(self) -> str:
        out = []
        t = self.totals
        out.append(
            f"# lines={t.get('lines_total', 0)} matched={t.get('lines_matched', 0)} "
            f"skipped={t.get('lines_skipped', 0)} backend={t.get('backend', '?')}"
        )
        if t.get("config_entries_skipped"):
            out.append(
                f"# WARNING: {t['config_entries_skipped']} config entries were "
                "skipped at parse time (lenient mode); their rules are not analyzed"
            )
        # group by ACL: key order is all configured rules first, then every
        # ACL's implicit deny, so naive sequential headers would repeat
        by_acl: dict[tuple[str, str], list[dict]] = {}
        for e in self.per_rule:
            by_acl.setdefault((e["firewall"], e["acl"]), []).append(e)
        # HLL error band (VERDICT Weak #6): every "unique sources" figure
        # is a sketch estimate; print its p90 band right next to it so a
        # deletion decision is never made on an uncaveated approximation
        hll = t.get("hll") or {}
        band = hll.get("rel_err_p90")
        band_txt = f" (±{100.0 * band:.1f}% p90)" if band else ""
        for (fw, acl), entries in by_acl.items():
            out.append(f"\n== {fw} / {acl} ==")
            for e in entries:
                tag = "implicit-deny" if e["index"] == 0 else f"rule {e['index']}"
                extra = ""
                if "unique_sources" in e:
                    extra = f"  uniq_src~{e['unique_sources']}{band_txt}"
                out.append(f"  {tag:>14}: {e['hits']:>12}{extra}  | {e['text']}")
        if hll.get("hint"):
            out.append(f"\n# hint: {hll['hint']}")
        win = t.get("window") or {}
        if win.get("incomplete"):
            inc = win["incomplete"]
            out.append(
                f"\n# WINDOW INCOMPLETE: {inc.get('drops', 0)} line(s) "
                f"dropped ({', '.join(inc.get('reasons', []))}) — zero-hit "
                "rules in this window are NOT deletion evidence"
            )
        if t.get("quarantine"):
            q = t["quarantine"]
            out.append(
                f"\n# quarantined (rules removed by a live reload, counters "
                f"preserved): {q['hits']} hits across {len(q['rules'])} rule(s)"
            )
        out.append(f"\n# unused rules: {len(self.unused)}")
        # static-analysis join (runtime/staticanalysis.py): every unused
        # rule prints with its evidence class, so "no hits observed" is
        # never mistaken for "provably dead" (or vice versa)
        st = t.get("static") or {}
        cls_of: dict[str, str] = {}
        for cls, label in (
            ("safe_to_delete", "provably dead — safe to delete"),
            ("traffic_dependent", "reachable — traffic-dependent"),
            ("undecided", "undecided — witness budget exhausted"),
        ):
            for rule in (st.get("unused_classes") or {}).get(cls, []):
                cls_of[rule] = label
        for fw, acl, idx in self.unused:
            tag = cls_of.get(f"{fw} {acl} {idx}")
            out.append(
                f"  UNUSED {fw} {acl} rule {idx}"
                + (f"  [{tag}]" if tag else "")
            )
        if st:
            sm = st.get("meta", {})
            out.append(
                f"\n# static analysis: {sm.get('dead', 0)} provably dead "
                f"rule(s) of {sm.get('n_rules', 0)} "
                f"({sm.get('witnesses_checked', 0)} witness packets "
                "device-checked)"
            )
            for c in st.get("contradictions", []):
                out.append(
                    f"# CONTRADICTION: {c['rule']} has {c['hits']} hit(s) "
                    f"but a dead '{c['verdict']}' verdict — counters span "
                    "a ruleset reload, or the analyzer is wrong"
                )
        return "\n".join(out)


def build_report(
    packed: PackedRuleset,
    hits: dict[RuleKey, int],
    *,
    backend: str,
    totals: dict[str, Any] | None = None,
    unique_sources: dict[RuleKey, int] | None = None,
    talkers: dict[tuple[str, str], list[tuple[int, int]]] | None = None,
) -> Report:
    """Assemble the report from per-key hits (exact or estimated)."""
    from ..hostside.aclparse import u32_to_ip

    per_rule = []
    unused: list[RuleKey] = []
    for key_id, meta in enumerate(packed.key_meta):
        key: RuleKey = (meta.firewall, meta.acl, meta.index)
        h = int(hits.get(key, 0))
        entry = {
            "firewall": meta.firewall,
            "acl": meta.acl,
            "index": meta.index,
            "key_id": key_id,
            "hits": h,
            "text": meta.text,
        }
        if unique_sources is not None and key in unique_sources:
            entry["unique_sources"] = int(unique_sources[key])
        per_rule.append(entry)
        if not meta.implicit_deny and h == 0:
            unused.append(key)
    talk = {}
    for (fw, acl), items in (talkers or {}).items():
        # items carry uint32 v4 addresses OR pre-rendered labels (IPv6
        # talkers arrive as address/digest strings from pipeline.finalize)
        talk[f"{fw} {acl}"] = [
            [ip if isinstance(ip, str) else u32_to_ip(int(ip)), int(c)]
            for ip, c in items
        ]
    t = dict(totals or {})
    t["backend"] = backend
    t["n_rules"] = packed.n_rules
    t["n_unused"] = len(unused)
    if packed.parse_skips:
        # lenient-mode parse skips: the report must say the source config
        # wasn't fully parsed (those rules were never analyzable)
        t["config_entries_skipped"] = len(packed.parse_skips)
    return Report(per_rule=per_rule, unused=unused, totals=t, talkers=talk)


# ---------------------------------------------------------------------------
# Report diffing — the operator's delete-decision view, shared by the
# ``diff-reports`` CLI and the serve mode's window-over-window publication.
# ---------------------------------------------------------------------------


def diff_report_objs(old: dict, new: dict, top: int = 10) -> dict:
    """Diff two report JSON objects (``run --json`` / serve window shape).

    Rules unused in BOTH reports are the stable deletion candidates;
    newly-unused / newly-used rules are the churn to investigate.  Only
    rules present in both reports compare — ruleset churn is reported
    separately so a deleted rule never masquerades as "newly used".
    """

    def load(rep: dict):
        hits = {
            (e["firewall"], e["acl"], e["index"]): e["hits"]
            for e in rep.get("per_rule", [])
        }
        unused = {tuple(k) for k in rep.get("unused", [])}
        return hits, unused

    hits_a, unused_a = load(old)
    hits_b, unused_b = load(new)
    key_str = lambda k: f"{k[0]} {k[1]} {k[2]}"  # noqa: E731
    common = set(hits_a) & set(hits_b)
    rules_removed = sorted(set(hits_a) - common)
    rules_added = sorted(set(hits_b) - common)
    movers = sorted(
        ((abs(hits_b[k] - hits_a[k]), k) for k in common), reverse=True
    )[:top]
    out = {
        "stable_unused": [key_str(k) for k in sorted(unused_a & unused_b & common)],
        "newly_unused": [key_str(k) for k in sorted((unused_b - unused_a) & common)],
        "newly_used": [key_str(k) for k in sorted((unused_a - unused_b) & common)],
        "rules_added": [key_str(k) for k in rules_added],
        "rules_removed": [key_str(k) for k in rules_removed],
        "top_hit_movers": [
            {"rule": key_str(k), "old": hits_a[k], "new": hits_b[k]}
            for d, k in movers
            if d > 0
        ],
    }
    # verdict-transition awareness (ISSUE 12): when BOTH reports carry
    # static-analysis verdicts, a rule moving reachable -> shadowed
    # across a reload is a typed diff row — an operator must see that a
    # rule DIED (config-order change), not just a silent count change
    verd_a = {
        (e["firewall"], e["acl"], e["index"]): e["verdict"]
        for e in old.get("per_rule", [])
        if "verdict" in e
    }
    verd_b = {
        (e["firewall"], e["acl"], e["index"]): e["verdict"]
        for e in new.get("per_rule", [])
        if "verdict" in e
    }
    if verd_a and verd_b:
        out["verdict_transitions"] = [
            {"rule": key_str(k), "old": verd_a[k], "new": verd_b[k]}
            for k in sorted(set(verd_a) & set(verd_b) & common)
            if verd_a[k] != verd_b[k]
        ]
    # serve-mode reports: surface incompleteness so a diff over a lossy
    # window is never mistaken for clean churn evidence
    inc = [
        label
        for label, rep in (("old", old), ("new", new))
        if (rep.get("totals", {}).get("window") or {}).get("incomplete")
    ]
    if inc:
        out["window_incomplete"] = inc
    return out


def window_of(rep: dict) -> tuple[str, float] | None:
    """``(mode, length)`` of a report's analysis window, or None.

    Batch reports carry no window; serve window reports carry
    ``totals.window.mode/length``; merged/cumulative serve views carry a
    window block without a single length and return None too (they are
    not same-window-comparable as-is).
    """
    win = rep.get("totals", {}).get("window") or {}
    if "mode" in win and "length" in win and "id" in win:
        return (str(win["mode"]), float(win["length"]))
    return None


def parse_window_spec(spec: str) -> tuple[str, float]:
    """``lines:N`` / ``900s`` / ``15m`` / ``24h`` / ``7d`` -> (mode, length)."""
    s = spec.strip().lower()
    if s.startswith("lines:"):
        try:
            n = int(s[len("lines:"):])
        except ValueError as e:
            raise AnalysisError(f"bad window spec {spec!r}") from e
        if n < 1:
            raise AnalysisError(f"window line count must be >= 1, got {n}")
        return ("lines", float(n))
    mult = 1.0
    if s and s[-1] in "smhd":
        mult = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}[s[-1]]
        s = s[:-1]
    try:
        sec = float(s) * mult
    except ValueError as e:
        raise AnalysisError(
            f"bad window spec {spec!r} (want lines:N or a duration like "
            "900s / 15m / 24h)"
        ) from e
    if sec <= 0:
        raise AnalysisError(f"window duration must be > 0, got {spec!r}")
    return ("sec", sec)


def check_window_compat(old: dict, new: dict, expect: str) -> None:
    """Typed refusal when two reports' windows don't match ``expect``.

    Comparing a 24h window against a 7d window produces a *misleading*
    diff — every quiet-in-24h rule reads as newly-unused — so
    ``diff-reports --expect-window`` turns that mistake into an error
    instead of an answer.
    """
    want = parse_window_spec(expect)
    for label, rep in (("old", old), ("new", new)):
        got = window_of(rep)
        if got is None:
            raise AnalysisError(
                f"--expect-window {expect}: the {label} report carries no "
                "per-window metadata (not a serve window report, or a "
                "merged/cumulative view)"
            )
        if got != want:
            raise AnalysisError(
                f"--expect-window {expect}: the {label} report's window is "
                f"{got[0]}:{got[1]:g}, expected {want[0]}:{want[1]:g} — "
                "reports from different window lengths are not comparable"
            )


# ---------------------------------------------------------------------------
# Window lineage (DESIGN §24).  A published window's provenance record:
# who contributed (hosts + delivered WAL seq ranges + loss accounting),
# which supervisor term published it, and which path it took
# (live | replay | backlog_heal).  The CORE of the record — everything
# except HOW/WHEN it was published — is a deterministic function of the
# delivered lines, so a failover republication must reproduce it
# bit-for-bit; term/path/publish stamp are the volatile envelope.
# ---------------------------------------------------------------------------

#: lineage fields that legitimately differ between a live publication
#: and a failover replay of the SAME window (the replay-identity law
#: strips exactly these before comparing)
LINEAGE_VOLATILE = ("term", "path", "published_unix", "crc")


def lineage_core(rec: dict) -> dict:
    """The deterministic core: the record minus its volatile envelope."""
    return {k: v for k, v in rec.items() if k not in LINEAGE_VOLATILE}


def seal_lineage(rec: dict) -> dict:
    """Stamp ``crc`` = CRC32 of the canonical-JSON core, in place.

    The CRC covers ONLY the core, so replay-identical windows carry
    identical CRCs even though their term/path differ — one u32 equality
    is the cheap audit for "same evidence, different publisher".
    """
    import zlib

    core = json.dumps(
        lineage_core(rec), sort_keys=True, separators=(",", ":")
    ).encode("utf-8")
    rec["crc"] = zlib.crc32(core) & 0xFFFFFFFF
    return rec


def lineage_frontier(records: list[dict]) -> dict:
    """The operator's "where did it stop" join (tools/doctor.py).

    From a lineage log: the last window published with COMPLETE evidence
    (no incomplete marker), the first window that is missing from the
    log or carries an incomplete marker, and the contiguity gaps — the
    three facts a postmortem needs before replaying anything.
    """
    by_window: dict[int, dict] = {}
    for r in records:
        if isinstance(r.get("window"), int) and r.get("kind") != "merged":
            by_window[r["window"]] = r  # last write wins (replay republish)
    if not by_window:
        return {"windows": 0, "last_complete": None, "first_incomplete": None,
                "gaps": []}
    ids = sorted(by_window)
    gaps = [w for w in range(ids[0], ids[-1] + 1) if w not in by_window]
    last_complete = None
    first_incomplete = gaps[0] if gaps else None
    for w in ids:
        if by_window[w].get("incomplete"):
            if first_incomplete is None or w < first_incomplete:
                first_incomplete = w
        else:
            last_complete = w
    return {
        "windows": len(ids),
        "last_complete": last_complete,
        "first_incomplete": first_incomplete,
        "gaps": gaps,
    }


# ---------------------------------------------------------------------------
# Per-rule trend events (ROADMAP item 3 pre-work).  A rule whose hit
# RATE jumps or collapses window-over-window is the churn an operator
# investigates before citing the report in a deletion decision; the
# threshold is multiplicative with a minimum-hits floor and the caller
# keeps a per-rule state dict so a multi-window ramp emits ONE event per
# transition, never a storm (steady load emits nothing at all).
# ---------------------------------------------------------------------------

#: below this many hits in BOTH windows a rule's ratio is noise, not a
#: trend (a 0->3 hop would otherwise read as an infinite burst)
TREND_MIN_HITS = 32


def trend_events(
    old: dict,
    new: dict,
    *,
    threshold: float,
    state: dict,
    min_hits: int = TREND_MIN_HITS,
) -> list[dict]:
    """Diff per-rule hit rates between consecutive window reports.

    ``rule_burst``: the new rate exceeds ``threshold`` x the old rate
    (and the new window has >= ``min_hits`` hits).  ``rule_quiet``: the
    old rate exceeded the floor and the new rate fell under old /
    ``threshold``.  Rates normalise by each window's delivered lines, so
    an ingest lull does not read as every rule going quiet.  ``state``
    maps rule key -> the last emitted label; an event is returned only
    on label CHANGE (hysteresis — re-asserting "still bursting" every
    window is the storm this flag exists to prevent).
    """

    def load(rep: dict) -> tuple[dict, float]:
        hits = {
            (e["firewall"], e["acl"], e["index"]): int(e["hits"])
            for e in rep.get("per_rule", [])
        }
        lines = float(rep.get("totals", {}).get("lines_total") or 0.0)
        return hits, max(lines, 1.0)

    hits_a, lines_a = load(old)
    hits_b, lines_b = load(new)
    key_str = lambda k: f"{k[0]} {k[1]} {k[2]}"  # noqa: E731
    events: list[dict] = []
    for k in sorted(set(hits_a) & set(hits_b)):
        ha, hb = hits_a[k], hits_b[k]
        ra, rb = ha / lines_a, hb / lines_b
        label = None
        if hb >= min_hits and rb > ra * threshold:
            label = "rule_burst"
        elif ha >= min_hits and rb < ra / threshold:
            label = "rule_quiet"
        ks = key_str(k)
        prev = state.get(ks)
        if label is None:
            # back inside the band: clear the state so a LATER burst of
            # the same rule is a fresh transition, but emit nothing
            if prev is not None:
                state.pop(ks, None)
            continue
        if label == prev:
            continue  # still bursting/quiet: hysteresis swallows it
        state[ks] = label
        events.append({
            "event": label,
            "rule": ks,
            "old_hits": ha,
            "new_hits": hb,
            "old_rate": round(ra, 9),
            "new_rate": round(rb, 9),
        })
    return events
