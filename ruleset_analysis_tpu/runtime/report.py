"""The unused-rule report — the reference's L5 layer (SURVEY.md §2, §4.5).

Reference semantics: set-difference of all configured rules minus rules with
hits, ordered per ACL; plus per-rule hit counts.  The TPU rebuild adds the
sketched statistics (estimated counts, per-rule unique-source cardinality,
top talkers) to the same report structure.

Pure host code; consumes plain dicts so both the oracle backend and the TPU
backend feed it.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

from ..hostside.oracle import RuleKey
from ..hostside.pack import PackedRuleset


@dataclasses.dataclass
class Report:
    """One analysis run's full output."""

    per_rule: list[dict]  # one entry per key, config order
    unused: list[RuleKey]
    totals: dict
    talkers: dict  # "<fw> <acl>" -> [[src_ip_str, count], ...]

    def to_json(self) -> str:
        return json.dumps(
            {
                "totals": self.totals,
                "per_rule": self.per_rule,
                "unused": [list(k) for k in self.unused],
                "talkers": self.talkers,
            },
            indent=2,
        )

    def to_text(self) -> str:
        out = []
        t = self.totals
        out.append(
            f"# lines={t.get('lines_total', 0)} matched={t.get('lines_matched', 0)} "
            f"skipped={t.get('lines_skipped', 0)} backend={t.get('backend', '?')}"
        )
        if t.get("config_entries_skipped"):
            out.append(
                f"# WARNING: {t['config_entries_skipped']} config entries were "
                "skipped at parse time (lenient mode); their rules are not analyzed"
            )
        # group by ACL: key order is all configured rules first, then every
        # ACL's implicit deny, so naive sequential headers would repeat
        by_acl: dict[tuple[str, str], list[dict]] = {}
        for e in self.per_rule:
            by_acl.setdefault((e["firewall"], e["acl"]), []).append(e)
        for (fw, acl), entries in by_acl.items():
            out.append(f"\n== {fw} / {acl} ==")
            for e in entries:
                tag = "implicit-deny" if e["index"] == 0 else f"rule {e['index']}"
                extra = ""
                if "unique_sources" in e:
                    extra = f"  uniq_src~{e['unique_sources']}"
                out.append(f"  {tag:>14}: {e['hits']:>12}{extra}  | {e['text']}")
        out.append(f"\n# unused rules: {len(self.unused)}")
        for fw, acl, idx in self.unused:
            out.append(f"  UNUSED {fw} {acl} rule {idx}")
        return "\n".join(out)


def build_report(
    packed: PackedRuleset,
    hits: dict[RuleKey, int],
    *,
    backend: str,
    totals: dict[str, Any] | None = None,
    unique_sources: dict[RuleKey, int] | None = None,
    talkers: dict[tuple[str, str], list[tuple[int, int]]] | None = None,
) -> Report:
    """Assemble the report from per-key hits (exact or estimated)."""
    from ..hostside.aclparse import u32_to_ip

    per_rule = []
    unused: list[RuleKey] = []
    for key_id, meta in enumerate(packed.key_meta):
        key: RuleKey = (meta.firewall, meta.acl, meta.index)
        h = int(hits.get(key, 0))
        entry = {
            "firewall": meta.firewall,
            "acl": meta.acl,
            "index": meta.index,
            "key_id": key_id,
            "hits": h,
            "text": meta.text,
        }
        if unique_sources is not None and key in unique_sources:
            entry["unique_sources"] = int(unique_sources[key])
        per_rule.append(entry)
        if not meta.implicit_deny and h == 0:
            unused.append(key)
    talk = {}
    for (fw, acl), items in (talkers or {}).items():
        # items carry uint32 v4 addresses OR pre-rendered labels (IPv6
        # talkers arrive as address/digest strings from pipeline.finalize)
        talk[f"{fw} {acl}"] = [
            [ip if isinstance(ip, str) else u32_to_ip(int(ip)), int(c)]
            for ip, c in items
        ]
    t = dict(totals or {})
    t["backend"] = backend
    t["n_rules"] = packed.n_rules
    t["n_unused"] = len(unused)
    if packed.parse_skips:
        # lenient-mode parse skips: the report must say the source config
        # wasn't fully parsed (those rules were never analyzable)
        t["config_entries_skipped"] = len(packed.parse_skips)
    return Report(per_rule=per_rule, unused=unused, totals=t, talkers=talk)
