"""Flow-coalescing policy for the stream drivers (ISSUE 5 tentpole).

ASA flow logs are massively repetitive — the same 5-tuple logs
106100/302013/302015 lines over and over — so every batch compacts into
(unique row, weight) pairs on the host before it crosses the wire
(``hostside.pack.coalesce_*``).  The device step is SCATTER-BOUND
(DESIGN §8: ~77% of the step is batch-sized register scatters), and
every register update is weight-linear or idempotent, so shrinking the
batch to its distinct rows shrinks the dominant scatters, the H2D
bytes, and the device rows near-linearly with traffic skew while the
final report stays bit-identical (DESIGN §11).

This module owns the *policy* around the compactors:

- **Bucket ladder.**  jit compiles one executable per static batch
  shape, so a coalesced batch of U unique rows pads up to the smallest
  bucket of a fixed geometric ladder (batch, batch/2, ... down to a
  floor that keeps mesh divisibility).  At most ``_LADDER_STEPS``
  distinct shapes ever compile; padding columns carry weight 0 and are
  masked on device like any invalid row.

- **auto mode.**  Compaction costs one O(B) host hash pass per batch;
  it pays for itself only when the compaction ratio r = raw/unique
  makes the device-step savings (~(1 - 1/r) x the scatter-bound share)
  exceed that pass.  ``auto`` coalesces the first
  ``AUTO_SAMPLE_BATCHES`` batches, and disables itself for the rest of
  the run when the observed ratio is below ``AUTO_MIN_RATIO`` — a
  uniform (ratio~1) corpus then pays only the sampling window.

- **Accounting.**  Raw-vs-unique row counters feed an
  ``ingest.coalesce`` trace span per batch, a metrics-snapshotter
  sampler, and the report's ``totals.coalesce`` block.  Committed line
  counters and elastic cursors are untouched: batch boundaries stay
  raw-line-based (coalescing happens strictly downstream of the batch
  iterator), so checkpoints and resume offsets are unchanged.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..config import AnalysisConfig
from ..hostside import pack as pack_mod
from . import faults, obs

#: ``auto`` samples this many batches before deciding...
AUTO_SAMPLE_BATCHES = 4
#: ...or this many raw rows, whichever comes first — a 1M-row-batch run
#: must not spend 4M rows deciding what half a million already show.
AUTO_SAMPLE_ROWS = 1 << 19
#: Minimum sampled compaction ratio (raw rows / unique rows) for
#: ``auto`` to keep coalescing.  Below it the host hash pass buys less
#: device-step shrink than it costs (DESIGN §11 threshold model).
AUTO_MIN_RATIO = 1.25
#: Maximum distinct coalesced batch shapes per family (compile bound).
_LADDER_STEPS = 6


def _ladder(batch_size: int, n_dev: int) -> list[int]:
    """Descending bucket sizes: halve while mesh-divisible, bounded."""
    out = [batch_size]
    while (
        len(out) < _LADDER_STEPS
        and out[-1] % 2 == 0
        and out[-1] // 2 >= n_dev
        and (out[-1] // 2) % n_dev == 0
    ):
        out.append(out[-1] // 2)
    return out


class Coalescer:
    """Per-run coalescing state shared by every driver hook.

    Thread-safe: under pipelined ingest the v4 hooks run on the producer
    thread while the v6 staging hooks run on the consumer, so the
    counters and the auto decision take a small lock (one uncontended
    acquire per *batch*, not per row).
    """

    def __init__(self, mode: str, batch_size: int, n_dev: int):
        if mode not in ("on", "auto"):
            raise ValueError(f"coalesce mode must be 'on' or 'auto', got {mode!r}")
        self.mode = mode
        self._enabled = True
        self._decided = mode == "on"
        self._lock = threading.Lock()
        self._ladder = _ladder(batch_size, max(n_dev, 1))
        self.batches = 0
        self.raw_rows = 0
        self.unique_rows = 0
        self._t0: float | None = None

    # -- policy ---------------------------------------------------------
    def enabled(self) -> bool:
        return self._enabled

    def _bucket(self, u: int) -> int:
        for size in reversed(self._ladder):  # ascending
            if size >= u:
                return size
        return self._ladder[0]

    def _account(self, raw: int, unique: int, t0: float, t1: float) -> None:
        with self._lock:
            if self._t0 is None:
                self._t0 = t0
            self.batches += 1
            self.raw_rows += raw
            self.unique_rows += unique
            if not self._decided and (
                self.batches >= AUTO_SAMPLE_BATCHES
                or self.raw_rows >= AUTO_SAMPLE_ROWS
            ):
                self._decided = True
                if self.raw_rows < AUTO_MIN_RATIO * max(self.unique_rows, 1):
                    # uniform-ish traffic: the hash pass costs more than
                    # the device shrink buys — stop coalescing (later
                    # batches pass through exactly as with --coalesce off)
                    self._enabled = False
        obs.complete(
            "ingest.coalesce", t0, t1, cat="ingest",
            args={"raw": raw, "unique": unique},
        )

    def _compact(self, mat: np.ndarray, fn, pad: bool) -> np.ndarray:
        # a failing compactor must abort typed, never emit a half-built
        # weighted batch (the chaos invariant; site registered in faults)
        faults.fire("ingest.coalesce.fail")
        t0 = time.perf_counter()
        raw = int(mat[-1].sum())
        out = fn(mat)
        u = out.shape[-1]
        if pad:
            out = pack_mod.pad_weighted(out, self._bucket(u))
        self._account(raw, u, t0, time.perf_counter())
        return out

    # -- family/layout hooks -------------------------------------------
    def tuple4(self, batch: np.ndarray, pad: bool = True) -> np.ndarray:
        """``[TUPLE_COLS, B]`` -> weighted ``[TUPLE_COLS, bucket]``."""
        return self._compact(batch, pack_mod.coalesce_batch, pad)

    def tuple6(self, batch6: np.ndarray, pad: bool = True) -> np.ndarray:
        return self._compact(batch6, pack_mod.coalesce_batch6, pad)

    def wire4(self, wire: np.ndarray, pad: bool = True) -> np.ndarray:
        """``[WIRE_COLS(+1), B]`` -> weighted ``[WIREW_COLS, bucket]``."""
        view = pack_mod._wire_weighted_view(
            wire, pack_mod.WIRE_COLS, pack_mod.W_META
        )
        return self._compact(view, pack_mod.coalesce_wire, pad)

    def wire6(self, wire6: np.ndarray, pad: bool = True) -> np.ndarray:
        view = pack_mod._wire_weighted_view(
            wire6, pack_mod.WIRE6_COLS, pack_mod.W6_META
        )
        return self._compact(view, pack_mod.coalesce_wire6, pad)

    # -- reporting ------------------------------------------------------
    def ratio(self) -> float:
        return self.raw_rows / max(self.unique_rows, 1)

    def summary(self) -> dict:
        """Report-totals block (``totals.coalesce``)."""
        return {
            "mode": self.mode,
            "active": self._enabled,
            "batches": self.batches,
            "raw_rows": self.raw_rows,
            "unique_rows": self.unique_rows,
            "compaction_ratio": round(self.ratio(), 4),
        }

    def sample_metrics(self) -> dict:
        """Live gauge for the metrics snapshotter (raw vs unique rows/s)."""
        elapsed = (
            time.perf_counter() - self._t0 if self._t0 is not None else 0.0
        )
        return {
            "mode": self.mode,
            "active": self._enabled,
            "batches": self.batches,
            "raw_rows": self.raw_rows,
            "unique_rows": self.unique_rows,
            "compaction_ratio": round(self.ratio(), 4),
            "raw_rows_per_sec": (
                round(self.raw_rows / elapsed, 1) if elapsed > 0 else 0.0
            ),
            "unique_rows_per_sec": (
                round(self.unique_rows / elapsed, 1) if elapsed > 0 else 0.0
            ),
        }


def make_coalescer(
    cfg: AnalysisConfig, batch_size: int, n_dev: int
) -> Coalescer | None:
    """One Coalescer per run, or None when ``cfg.coalesce`` is off.

    ``None`` keeps the off path at literally zero added work — the
    drivers' hooks are one ``is not None`` check per batch.
    """
    if cfg.coalesce == "off":
        return None
    return Coalescer(cfg.coalesce, batch_size, n_dev)
