"""Multi-tenant serve: thousands of rulesets on one mesh (ISSUE 16).

:class:`TenantServeDriver` is the tenancy-plane twin of
``serve.py::ServeDriver``: one process, one mesh, one listener queue —
but N independent tenants, each with its OWN ruleset, register plane,
window clock, report ring, quarantine bucket, and latency histogram.
It composes the single-tenant service's building blocks rather than
forking them:

- **Ingest.**  One shared :class:`~.tenancy.TenantLineQueue`; listeners
  bound to a tenant in the manifest enqueue through a
  :class:`~.tenancy.TenantTap` (provenance rides with the line), shared
  listeners enqueue untagged and the :class:`~.tenancy.TenantRouter`
  resolves at consume time (explicit ``@tenant`` tag > listener >
  syslog hostname > manifest default).  Unroutable lines are counted
  (``lines_unrouted_total``), never guessed.  With ``--wal`` every
  routed line spools durably WITH its tenant key (wal.py record v2).

- **Device.**  :class:`~.tenancy.TenantEngine` owns the bucketed rule /
  register stacks and one never-specialized compiled step per bucket
  geometry; this driver interleaves tenants' batches freely because
  every register plane is tenant-sliced (per-tenant reports are
  bit-identical to solo runs — property-tested).

- **Windows.**  Each tenant rotates on its OWN clock: lines-mode
  counts that tenant's lines; wall-mode staggers the lanes across the
  cadence so N publishes never stampede one instant.  Rotation pulls
  ONE tenant's plane to host, publishes under ``serve_dir/t/<name>/``,
  and zeroes only that tenant's slice.

- **Hot reload, isolated.**  ``request_reload(name)`` re-packs ONE
  tenant: its inflight batch flushes, its static verdicts re-compute,
  its registers/ring/trackers migrate through the same MigrationMap
  machinery (stamped with the tenant key), and the engine swaps one
  slice of a traced rule stack — no recompile, no flush, no paused
  window for any other tenant (pinned by test).  A failed reload is
  atomic per tenant: that lane keeps its old ruleset and counters.

- **Fairness + SLO.**  The shared queue is the fairness boundary:
  per-tenant routed/consumed counters and share fractions are first-
  class ``/metrics`` gauges (JSON and Prometheus ``{tenant=...}``
  labels via ``autoscale.render_prom_labeled``), so a noisy tenant
  starving the ring is visible, not silent.  Ingest->publish latency
  keeps one log2-bucket histogram PER TENANT plus the aggregate, with
  p50/p90/p99 gauges derived from the same counts the prom buckets
  expose.

Deliberate scope bounds (typed refusals, not silent downgrades):
``--resume``/ring checkpointing, ``--autoscale``, IPv6 tenant rules,
and stacked/coalesced layouts stay single-tenant features for now.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

import numpy as np

from ..config import AnalysisConfig, ServeConfig
from ..errors import AnalysisError, FeedWorkerError, StallError
from ..hostside import pack as pack_mod
from ..hostside.listener import ListenerSet, make_listener
from ..models import pipeline
from ..ops.topk import TopKTracker
from . import devprof, epochstore, faults, flightrec, obs, retrypolicy
from .autoscale import render_prom, render_prom_labeled
from .metrics import (
    LatencyHistogram,
    SloBurnEngine,
    SloPolicy,
    build_info,
    render_build_info_prom,
    window_slo_stats,
)
from .report import diff_report_objs, seal_lineage, trend_events
from .serve import (
    WindowEpoch,
    WindowRing,
    _merge_quarantine,
    _quarantine_totals,
    build_migration,
    merge_register_arrays,
    migrate_arrays,
    migrate_tracker_tables,
    zero_arrays,
)
from .tenancy import (
    TenantEngine,
    TenantLineQueue,
    TenantRouter,
    TenantTap,
    load_manifest,
)
from .wal import LineageLog, WriteAheadLog


class _ReloadFlushError(Exception):
    """A step failure while flushing the reloading lane's inflight batch
    — the analysis is broken, not the reload; re-raised as the cause."""


class _Lane:
    """One tenant's host-side serve state (windows, ring, counters).

    The device-side twin is the tenant's slice in the engine's bucket
    stacks; everything here is plain host bookkeeping, so lanes are
    fully independent — the isolation guarantee falls out of the
    structure instead of needing locks per field.
    """

    def __init__(self, spec, packed):
        self.name = spec.name
        self.spec = spec
        self.packed = packed
        self.ring: WindowRing | None = None  # sized in run()
        self.published: dict[str, dict] = {}
        self.window_reports: dict[int, dict] = {}
        self.cum_arrays: dict[str, np.ndarray] | None = None
        self.cum_tracker: TopKTracker | None = None
        self.cum_quarantine: dict[tuple, int] = {}
        self.cum_incomplete_reasons: list[str] = []
        self.cum_incomplete_windows: list[int] = []
        self.lat_cum = LatencyHistogram()
        self.windows_published = 0
        self.total_lines = 0
        self.total_parsed = 0
        self.total_skipped = 0
        self.total_chunks = 0
        self.routed_total = 0  # lines routed here (incl. not-yet-windowed)
        self.talker_entries_dropped = 0
        self.reloads = 0
        self.reload_errors = 0
        self.last_reload_error = ""
        # static-analysis plane (per tenant: a reload re-verdicts ONLY
        # its own lane)
        self.sa = None
        self.static_obj: dict | None = None
        self.static_done_t: float | None = None
        self.static_duration = 0.0
        # lineage + trend planes (DESIGN §24), per lane: ring-retained
        # provenance records and the per-rule hysteresis labels
        self.lineage_recent: dict[int, dict] = {}
        self._trend_state: dict[str, str] = {}
        # durable epoch store (DESIGN §25), per lane: one tenant's
        # history never shares segments with another's
        self.store = None
        # window-local fields are (re)set by _begin_window
        self.win_id = 0
        self.next_rotation: float | None = None


class TenantServeDriver:
    """The multi-tenant always-on service (one process, one mesh).

    Same lifecycle contract as ``ServeDriver``: construction loads and
    validates everything host-side (manifest, packed rulesets, listener
    and HTTP binds), the blocking :meth:`run` owns the device loop, and
    tests drive it from a thread through the listeners / HTTP endpoint.
    """

    def __init__(
        self,
        manifest_path: str,
        cfg: AnalysisConfig,
        scfg: ServeConfig,
        *,
        topk: int = 10,
        mesh=None,
        distributed=None,
    ):
        if distributed is not None:
            raise AnalysisError(
                "serve --tenants and --distributed do not compose yet: "
                "the tenancy plane multiplexes rulesets on ONE mesh while "
                "the host tier shards ingest of ONE ruleset across many "
                "(DESIGN §22 scope bound); run one distributed service "
                "per tenant, or drop --distributed"
            )
        if cfg.layout != "flat":
            raise AnalysisError(
                "serve --tenants supports layout='flat' only (the stacked "
                "group buffer has no window boundary semantics)"
            )
        if cfg.coalesce != "off":
            raise AnalysisError(
                "serve --tenants does not support --coalesce; the tenancy "
                "plane applies the geometric ladder to RULE shapes instead"
            )
        if cfg.resume:
            raise AnalysisError(
                "serve --tenants does not support --resume yet: the ring "
                "checkpoint format is single-tenant (ROADMAP scope bound); "
                "drop --resume"
            )
        self.manifest_path = manifest_path
        self.cfg = cfg
        self.scfg = scfg
        self.topk = topk
        self._mesh_arg = mesh
        self.specs = load_manifest(manifest_path)
        self.router = TenantRouter(self.specs)
        self.lanes: dict[str, _Lane] = {}
        for spec in self.specs:
            try:
                packed = pack_mod.load_packed(spec.ruleset)
            except OSError as e:
                raise AnalysisError(
                    f"tenant {spec.name!r}: cannot read packed ruleset "
                    f"{spec.ruleset!r}: {e}"
                ) from e
            self.lanes[spec.name] = _Lane(spec, packed)
        self.queue = TenantLineQueue(scfg.queue_lines)
        # one ListenerSet over one shared queue; tenant-bound listeners
        # enqueue through a TenantTap so provenance rides with the line
        self.listeners = ListenerSet(self.queue, [])
        def _add(spec_str: str, tenant: str | None) -> None:
            ln = make_listener(TenantTap(self.queue, tenant), spec_str)
            # tenant provenance + index in the label: endpoint.json and
            # /health key addresses by label, and two port-0 binds would
            # otherwise collide
            ln.label = (
                f"{ln.label}#{len(self.listeners.listeners)}"
                f"@{tenant or 'shared'}"
            )
            self.listeners.listeners.append(ln)

        try:
            for spec_str in scfg.listen:
                _add(spec_str, None)
            for spec in self.specs:
                for spec_str in spec.listen:
                    _add(spec_str, spec.name)
        except BaseException:
            self.listeners.close()
            raise
        if not self.listeners.listeners:
            raise AnalysisError(
                "serve --tenants needs at least one listener: --listen or a "
                "per-tenant 'listen' in the manifest"
            )
        self._reload_req = threading.Event()
        self._reload_names: deque[str] = deque()  # empty + event set = all
        self._reload_lock = threading.Lock()
        self._stop_req = threading.Event()
        self._pub_lock = threading.Lock()
        self._deg_lock = threading.Lock()
        self.degraded: dict[str, str] = {}
        self.degraded_events = 0
        self.recovered_events = 0
        self._http = None
        if scfg.http != "off":
            host, _, port = scfg.http.rpartition(":")
            try:
                self._http = _make_tenant_http_server((host, int(port)), self)
            except BaseException:
                self.listeners.close()
                raise
        self._http_thread = None
        self._watch_thread = None
        self._old_signals: dict = {}
        # service-wide counters
        self.windows_published = 0
        self.reloads = 0
        self.reload_errors = 0
        self.lines_consumed_total = 0
        self.lines_unrouted_total = 0
        self.total_lines = 0
        self.lat_cum = LatencyHistogram()  # aggregate across tenants
        self.wal: WriteAheadLog | None = None
        self.world = 0  # mesh extent, set in run()
        self._t0 = time.time()
        # lineage + SLO planes (DESIGN §24): no lease on the tenancy
        # tier, so term stays 0 and every path is "live" (no --resume)
        self.term = 0
        self._wal_next = 0  # shared-WAL cursor (record v2, all tenants)
        self._lineage_log: LineageLog | None = None
        self.lineage_records_total = 0
        self.trend_events_total = 0
        self.slo = (
            SloBurnEngine(SloPolicy.parse(scfg.slo)) if scfg.slo else None
        )

    # -- public control surface -------------------------------------------
    def request_reload(self, tenant: str | None = None) -> None:
        """Queue a hot reload: one tenant, or every tenant (SIGHUP)."""
        with self._reload_lock:
            if tenant is not None:
                self._reload_names.append(tenant)
        self._reload_req.set()

    def stop(self) -> None:
        self._stop_req.set()

    @property
    def http_address(self) -> tuple[str, int] | None:
        srv = self._http
        return tuple(srv.server_address[:2]) if srv is not None else None

    # -- degraded-mode plane (serve.py discipline, per-tenant subsystems) --
    def _degrade(self, subsystem: str, err) -> None:
        with self._deg_lock:
            if subsystem not in self.degraded:
                self.degraded_events += 1
                obs.instant(
                    "serve.degraded",
                    args={"subsystem": subsystem, "error": str(err)[:200]},
                )
            self.degraded[subsystem] = f"{type(err).__name__}: {err}" if isinstance(
                err, BaseException
            ) else str(err)

    def _recover(self, subsystem: str) -> None:
        with self._deg_lock:
            if subsystem in self.degraded:
                del self.degraded[subsystem]
                self.recovered_events += 1
                obs.instant("serve.recovered", args={"subsystem": subsystem})

    def degraded_set(self) -> list[str]:
        with self._deg_lock:
            return sorted(self.degraded)

    def _check_metrics_health(self) -> None:
        h = obs.metrics_health()
        if h is None:
            return
        if not h["alive"] or h["consec_errors"] > 0:
            self._degrade(
                "metrics", h["last_error"] or "metrics snapshotter thread died"
            )
        else:
            self._recover("metrics")

    # -- run --------------------------------------------------------------
    def run(self) -> dict:
        """Serve until stopped; returns a summary dict (also written to
        ``serve_dir/summary.json``)."""
        from ..parallel import mesh as mesh_lib

        scfg = self.scfg
        os.makedirs(scfg.serve_dir, exist_ok=True)
        armed_here = faults.arm_spec(self.cfg.fault_plan)
        retrypolicy.configure(self.cfg.retry_policy)
        if self.cfg.blackbox_dir:
            flightrec.arm(self.cfg.blackbox_dir, role="serve")
        aborted: BaseException | None = None
        try:
            mesh = self._mesh_arg or mesh_lib.make_mesh(
                axis=self.cfg.mesh_axis,
                topology=self.cfg.mesh_shape,
                dcn=self.cfg.mesh_dcn,
            )
            self.mesh = mesh
            self.world = mesh_lib.data_extent(mesh)
            self.batch_size = mesh_lib.pad_batch_size(
                self.cfg.batch_size, mesh, self.cfg.mesh_axis
            )
            self.engine = TenantEngine(
                mesh, self.cfg, {n: l.packed for n, l in self.lanes.items()}
            )
            flightrec.cursor(tenants=len(self.lanes))
            for lane in self.lanes.values():
                lane.ring = WindowRing(scfg.ring)
                lane.cum_arrays = zero_arrays(lane.packed.n_keys, self.cfg)
                lane.cum_tracker = TopKTracker(self.cfg.sketch.topk_capacity)
                if scfg.epoch_store:
                    # per-tenant sub-store, budget split evenly; like
                    # the shared WAL below there is no tenancy resume,
                    # so every run starts a fresh history
                    lane.store = epochstore.EpochStore(
                        os.path.join(
                            scfg.epoch_store, f"tenant-{lane.name}"
                        ),
                        budget_bytes=max(
                            1 << 20,
                            scfg.epoch_store_budget_bytes
                            // len(self.lanes),
                        ),
                        trend_threshold=scfg.trend_threshold,
                    )
                    lane.store.reset()
                    lane.store.bind_base(lane.win_id)
                    lane.store.set_labels([
                        (m.firewall, m.acl, m.index)
                        for m in lane.packed.key_meta
                    ])
                if scfg.static_analysis:
                    # initial analysis failures degrade ONE tenant's
                    # static plane; every other lane publishes verdicts
                    try:
                        sa, dur = self._compute_static(lane.packed, reuse=None)
                    except AnalysisError as e:
                        self._degrade(f"static_analysis:{lane.name}", e)
                    else:
                        self._publish_static(lane, sa, dur)
            if scfg.wal:
                self.wal = WriteAheadLog(
                    scfg.wal_dir or os.path.join(scfg.serve_dir, "wal"),
                    segment_bytes=scfg.wal_segment_bytes,
                    budget_bytes=scfg.wal_budget_bytes,
                )
                # no --resume on the tenancy plane yet: every run starts
                # a fresh spool (the record-v2 tenant key is exercised by
                # the wal-level replay tests)
                self.wal.reset()
            if scfg.lineage:
                # ONE shared provenance ledger; each record carries its
                # tenant key, mirroring the shared WAL's record-v2 law
                lpath = os.path.join(scfg.serve_dir, LineageLog.NAME)
                try:
                    os.remove(lpath)
                except OSError:
                    pass
                self._lineage_log = LineageLog(lpath)
            obs.register_sampler("listener", self._sample_metrics)
            obs.register_sampler("serve", self.metrics_gauges)
            self.listeners.start()
            now = time.monotonic()
            n = len(self.lanes)
            for i, name in enumerate(sorted(self.lanes)):
                lane = self.lanes[name]
                self._begin_window(lane)
                if scfg.window_sec:
                    # stagger first rotations across the cadence so N
                    # tenants never publish (and fsync) the same instant
                    lane.next_rotation = (
                        now + scfg.window_sec * (1.0 + i / n)
                    )
            self._start_http()
            self._start_watcher()
            self._install_signals()
            self._write_json("", "endpoint.json", {
                "pid": os.getpid(),
                "http": list(self.http_address) if self.http_address else None,
                "listeners": self.listeners.addresses(),
                "serve_dir": os.path.abspath(scfg.serve_dir),
                "tenants": sorted(self.lanes),
            })
            self._loop()
        except BaseException as e:
            aborted = e
            raise
        finally:
            try:
                self._teardown(aborted)
            finally:
                if armed_here:
                    faults.disarm()
        summary = {
            "tenants": {
                name: {
                    "windows_published": lane.windows_published,
                    "lines_total": lane.total_lines,
                    "reloads": lane.reloads,
                    "reload_errors": lane.reload_errors,
                    "quarantine_hits": int(sum(lane.cum_quarantine.values())),
                }
                for name, lane in sorted(self.lanes.items())
            },
            "windows_published": self.windows_published,
            "lines_total": self.total_lines,
            "lines_unrouted": self.lines_unrouted_total,
            "drops": self.queue.snapshot()["dropped"],
            "reloads": self.reloads,
            "reload_errors": self.reload_errors,
            "serve_dir": os.path.abspath(scfg.serve_dir),
            "world": self.world,
            "degraded": self.degraded_set(),
            "degraded_events": self.degraded_events,
            "recovered_events": self.recovered_events,
            "retry": retrypolicy.counters(),
        }
        if self.wal is not None:
            summary["wal"] = self.wal.stats()
        self._write_json("", "summary.json", summary)
        return summary

    # -- static analysis (per tenant) -------------------------------------
    def _compute_static(self, packed, reuse):
        from . import staticanalysis

        t0 = time.monotonic()
        with obs.span("serve.static_analysis"):
            sa = staticanalysis.analyze_ruleset(
                packed,
                witness_budget=self.scfg.static_witness_budget,
                reuse=reuse,
            )
        return sa, time.monotonic() - t0

    def _publish_static(self, lane: _Lane, sa, duration: float) -> None:
        obj = sa.to_obj(lane.packed)
        with self._pub_lock:
            self._install_static(lane, sa, obj, duration)
        self._write_json(lane.name, "static.json", obj)

    def _install_static(self, lane: _Lane, sa, obj, duration: float) -> None:
        """Caller holds ``_pub_lock`` (same joint-swap rule as serve.py)."""
        lane.sa = sa
        lane.static_obj = obj
        lane.published["static"] = obj
        lane.static_done_t = time.time()
        lane.static_duration = duration
        self._recover(f"static_analysis:{lane.name}")

    def _attach_static(self, lane: _Lane, obj: dict, *, strict: bool) -> dict:
        if lane.static_obj is None:
            return obj
        from . import staticanalysis

        return staticanalysis.attach_static_obj(
            obj, lane.static_obj, strict=strict
        )

    # -- window lifecycle (per lane) --------------------------------------
    def _begin_window(self, lane: _Lane) -> None:
        from .stream import LineBatcher

        packer = pack_mod.LinePacker(lane.packed)
        # the tenancy plane is v4-only (engine refuses rules6 rows), so
        # the batcher's v6 staging is permanently empty
        lane.batcher = LineBatcher(packer, False, [], {}, self.batch_size)
        lane.tracker = TopKTracker(self.cfg.sketch.topk_capacity)
        lane.pending = deque()
        lane.n_chunks = 0  # window-local candidate-table salt
        lane.win_lines = 0
        lane.win_pushed = 0
        lane.win_reloads = 0
        lane.win_quarantine = {}
        lane._win_t0 = time.time()
        lane._win_t0_mono = time.monotonic()
        lane._win_lat = LatencyHistogram()
        lane._win_receipts = []
        lane._recv_stride = 1
        lane._recv_i = 0
        base = getattr(lane, "_next_drops_base", None)
        lane._drops_at_start = (
            base if base is not None else self.queue.snapshot()["dropped"]
        )
        lane._listeners_ok_at_start = (
            self.listeners.alive() == len(self.listeners.listeners)
        )
        lane._win_saw_stall = False
        # lineage: the shared-WAL cursor when this lane's window opened
        # (the delivered range is a shared-fate bound, like drops)
        lane._win_wal_lo = int(self._wal_next)

    _RECEIPT_CAP = 4096

    def _note_receipt(self, lane: _Lane, t_recv: float) -> None:
        if lane._recv_i % lane._recv_stride == 0:
            lane._win_receipts.append(t_recv)
            if len(lane._win_receipts) >= self._RECEIPT_CAP:
                lane._win_receipts = lane._win_receipts[::2]
                lane._recv_stride *= 2
        lane._recv_i += 1

    def _drain(self, lane: _Lane, out: pipeline.ChunkOut) -> None:
        lane.tracker.offer_chunk(
            np.asarray(out.cand_acl),
            np.asarray(out.cand_src),
            np.asarray(out.cand_est),
        )

    def _consume_event(self, lane: _Lane, ev) -> None:
        batch_np, n_raw = ev
        if batch_np is None:
            lane.win_lines += n_raw
            obs.add_lines(n_raw)
            return
        out = self.engine.run_batch(lane.name, batch_np, salt=lane.n_chunks)
        lane.pending.append(out)
        if len(lane.pending) > 2:
            self._drain(lane, lane.pending.popleft())
        lane.n_chunks += 1
        lane.win_lines += n_raw
        obs.add_lines(n_raw)

    def _flush_inflight(self, lane: _Lane) -> None:
        """Step ONE lane's consumed-but-unstepped tail (rotation/reload
        barrier for that lane only — no other tenant flushes)."""
        tail = lane.batcher.flush()
        if tail is not None:
            self._consume_event(lane, tail)
        while lane.pending:
            self._drain(lane, lane.pending.popleft())

    def _window_meta(self, lane: _Lane, *, partial: bool) -> dict:
        drops = self.queue.snapshot()["dropped"] - lane._drops_at_start
        lane._next_drops_base = lane._drops_at_start + drops
        listeners_ok = (
            self.listeners.alive() == len(self.listeners.listeners)
        )
        reasons = []
        if drops > 0:
            # the queue is SHARED: a drop may be any tenant's line, so
            # every window the drop overlaps carries the marker — a
            # shared-fate bound is honest, a per-tenant guess is not
            reasons.append("dropped_lines")
        if lane._listeners_ok_at_start and not listeners_ok:
            reasons.append("listener_died")
        if not lane._listeners_ok_at_start:
            reasons.append("listener_down")
        if lane._win_saw_stall or self.listeners.stalled(
            self.cfg.stall_timeout_sec
        ):
            reasons.append("listener_stalled")
        packer = lane.batcher.packer
        meta = {
            "id": lane.win_id,
            "tenant": lane.name,
            "mode": "lines" if self.scfg.window_lines else "sec",
            "length": self.scfg.window_lines or self.scfg.window_sec,
            "lines": lane.win_lines,
            "parsed": packer.parsed,
            "skipped": packer.skipped,
            "chunks": lane.n_chunks,
            "drops": int(drops),
            "reloads": lane.win_reloads,
            "started_unix": round(lane._win_t0, 3),
            "ended_unix": round(time.time(), 3),
            "elapsed_sec": round(time.monotonic() - lane._win_t0_mono, 4),
        }
        if partial:
            meta["partial"] = True
        if reasons:
            meta["incomplete"] = {"drops": int(drops), "reasons": reasons}
        return meta

    def _window_totals(self, lane: _Lane, meta: dict, quarantine,
                       latency=None) -> dict:
        elapsed = meta.get(
            "elapsed_sec", max(meta["ended_unix"] - meta["started_unix"], 0.0)
        )
        totals = {
            "lines_total": meta["lines"],
            "lines_matched": meta["parsed"],
            "lines_skipped": meta["skipped"],
            "chunks": meta["chunks"],
            "elapsed_sec": round(elapsed, 4),
            "lines_per_sec": (
                round(meta["lines"] / elapsed, 1) if elapsed > 0 else 0.0
            ),
            "tenant": lane.name,
            "window": meta,
        }
        if latency:
            totals["latency"] = {"ingest_to_publish": latency}
        qt = _quarantine_totals(quarantine)
        if qt:
            totals["quarantine"] = qt
        deg = self.degraded_set()
        if deg:
            totals["degraded"] = deg
        return totals

    def _rotate(self, lane: _Lane, *, partial: bool = False) -> None:
        with obs.span("serve.rotate", window=lane.win_id, tenant=lane.name):
            self._flush_inflight(lane)
            t_pub = time.monotonic()
            for t_recv in lane._win_receipts:
                lane._win_lat.record(
                    max(t_pub - t_recv, 0.0), n=lane._recv_stride
                )
            lane.lat_cum.merge(lane._win_lat)
            self.lat_cum.merge(lane._win_lat)
            win_latency = (
                lane._win_lat.summary() if lane._win_lat.count else None
            )
            meta = self._window_meta(lane, partial=partial)
            # ONE tenant's plane comes to host; every other tenant's
            # slice stays on device, untouched
            arrays = self.engine.host_arrays(lane.name)
            ep = WindowEpoch(
                arrays=arrays,
                meta=meta,
                tracker_tables=lane.tracker.tables(),
                quarantine=dict(lane.win_quarantine),
            )
            rep = pipeline.finalize(
                pipeline.AnalysisState(**arrays), lane.packed, self.cfg,
                lane.tracker, topk=self.topk,
                totals=self._window_totals(
                    lane, meta, lane.win_quarantine, latency=win_latency
                ),
                v6_digests={},
            )
            rep_obj = self._attach_static(
                lane,
                json.loads(rep.to_json()),
                strict=meta.get("reloads", 0) == 0 and self.cfg.exact_counts,
            )
            if self.scfg.lineage:
                rep_obj["totals"]["lineage"] = self._assemble_lineage(
                    lane, meta
                )
            win_hist = lane._win_lat
            if meta.get("incomplete"):
                lane.cum_incomplete_windows.append(meta["id"])
                for r in meta["incomplete"]["reasons"]:
                    if r not in lane.cum_incomplete_reasons:
                        lane.cum_incomplete_reasons.append(r)
            with self._pub_lock:
                lane.ring.push(ep)
                prev = lane.published.get("report")
                _merge_quarantine(lane.cum_quarantine, lane.win_quarantine)
            lane.cum_arrays = merge_register_arrays([lane.cum_arrays, arrays])
            for acl, table in ep.tracker_tables.items():
                for src, est in table.items():
                    lane.cum_tracker.offer(int(acl), int(src), int(est))
            if (
                lane.store is not None
                and f"epoch_store:{lane.name}" not in self.degraded_set()
            ):
                # a spill failure degrades ONE tenant's history plane;
                # it stays off so the survivor's numbering stays dense
                try:
                    lane.store.spill(ep)
                except AnalysisError as e:
                    self._degrade(f"epoch_store:{lane.name}", e)
            lane.total_lines += meta["lines"]
            lane.total_parsed += meta["parsed"]
            lane.total_skipped += meta["skipped"]
            lane.total_chunks += meta["chunks"]
            self.total_lines += meta["lines"]
            # zero ONLY this tenant's register slice, open its next window
            self.engine.zero_tenant(lane.name)
            lane.win_id += 1
            self._begin_window(lane)
            lane.windows_published += 1
            self.windows_published += 1
            flightrec.cursor(
                tenant=lane.name,
                window=meta["id"],
                windows_published=self.windows_published,
            )
            obs.metric_event(
                "serve.window", tenant=lane.name, id=meta["id"],
                lines=meta["lines"], chunks=meta["chunks"],
                drops=meta["drops"],
            )
            self._publish(lane, rep_obj, prev, meta)
            self._observe_slo(lane, meta, win_hist)

    def _assemble_lineage(self, lane: _Lane, meta: dict) -> dict:
        """One tenant window's sealed provenance record (DESIGN §24).

        ``kind`` is "tenant" and the record carries the tenant key; the
        WAL range is the SHARED spool's cursor span over the lane's
        window (record v2 interleaves tenants), so like the drop marker
        it is a shared-fate bound, not a per-tenant slice.
        """
        rec: dict = {
            "window": meta["id"],
            "kind": "tenant",
            "tenant": lane.name,
            "hosts": [{
                "rank": 0,
                "wal_seq_lo": int(getattr(lane, "_win_wal_lo", 0)),
                "wal_seq_hi": int(self._wal_next),
                "drops": int(meta.get("drops", 0)),
                "quarantine_hits": int(sum(lane.win_quarantine.values())),
            }],
            "generation": int(lane.reloads),
            "term": int(self.term),
            "path": "live",
            "published_unix": round(time.time(), 3),
        }
        if meta.get("incomplete"):
            rec["incomplete"] = meta["incomplete"]
        return seal_lineage(rec)

    def _lineage_append(self, lane: _Lane, rec: dict) -> None:
        """Ledger one lane's record — CORE, same law as serve.py: the
        jsonl append precedes the window file and failures abort typed
        (a window must never publish without its provenance)."""
        if self._lineage_log is not None:
            self._lineage_log.append(rec)
        with self._pub_lock:
            lane.lineage_recent[rec["window"]] = rec
            live = set(lane.ring.window_ids())
            for wid in [w for w in lane.lineage_recent if w not in live]:
                del lane.lineage_recent[wid]
        self.lineage_records_total += 1

    def lineage_tail(self) -> dict:
        """The ``/lineage`` HTTP view: ring-retained records per lane."""
        with self._pub_lock:
            return {
                "records_total": self.lineage_records_total,
                "tenants": {
                    name: [
                        lane.lineage_recent[w]
                        for w in sorted(lane.lineage_recent)
                    ]
                    for name, lane in sorted(self.lanes.items())
                },
            }

    def _observe_slo(self, lane: _Lane, meta: dict, hist=None) -> None:
        """Feed one lane's published window to the burn-rate engine.

        ONE engine across tenants (the SLO guards the service, windows
        arrive interleaved); the breach event names the tenant whose
        window tripped it."""
        if self.slo is None:
            return
        stats = window_slo_stats(
            hist if (hist is not None and hist.count) else None,
            lines=int(meta.get("lines", 0)),
            drops=int(meta.get("drops", 0)),
            incomplete=bool(meta.get("incomplete")),
            degraded=len(self.degraded_set()),
            window=meta.get("id"),
        )
        for ev in self.slo.observe(stats):
            obs.typed_event(ev.pop("event"), tenant=lane.name, **ev)

    def _publish(self, lane: _Lane, rep_obj, prev, meta) -> None:
        with obs.span("serve.publish", window=meta["id"], tenant=lane.name):
            cum_obj = self._attach_static(
                lane,
                json.loads(self._render_cumulative(lane).to_json()),
                strict=False,
            )
            diff_obj = None
            if prev is not None:
                diff_obj = diff_report_objs(prev, rep_obj, top=self.topk)
                diff_obj["windows"] = [
                    prev["totals"].get("window", {}).get("id"), meta["id"],
                ]
                diff_obj["tenant"] = lane.name
                if self.scfg.trend_threshold > 0:
                    # per-rule quiet/burst events, per-lane hysteresis
                    # state (one tenant's burst never flaps another's)
                    evs = trend_events(
                        prev, rep_obj,
                        threshold=self.scfg.trend_threshold,
                        state=lane._trend_state,
                    )
                    if evs:
                        diff_obj["trend_events"] = evs
                        self.trend_events_total += len(evs)
                        for ev in evs:
                            obs.typed_event(
                                ev["event"], tenant=lane.name,
                                **{
                                    k: v for k, v in ev.items()
                                    if k != "event"
                                },
                            )
            lin = rep_obj.get("totals", {}).get("lineage")
            if lin is not None:
                self._lineage_append(lane, lin)
            with self._pub_lock:
                lane.published["report"] = rep_obj
                lane.published["cumulative"] = cum_obj
                if diff_obj is not None:
                    lane.published["diff"] = diff_obj
                lane.window_reports[meta["id"]] = rep_obj
                live = set(lane.ring.window_ids())
                evicted = [w for w in lane.window_reports if w not in live]
                for wid in evicted:
                    del lane.window_reports[wid]
            for wid in evicted:
                for fname in (f"window-{wid:06d}.json", f"diff-{wid:06d}.json"):
                    try:
                        os.remove(os.path.join(
                            self.scfg.serve_dir, "t", lane.name, fname
                        ))
                    except OSError:
                        pass
            self._write_json(lane.name, f"window-{meta['id']:06d}.json", rep_obj)
            self._write_json(lane.name, "latest.json", rep_obj)
            self._write_json(lane.name, "cumulative.json", cum_obj)
            if diff_obj is not None:
                self._write_json(
                    lane.name, f"diff-{meta['id']:06d}.json", diff_obj
                )

    def _render_cumulative(self, lane: _Lane):
        q = lane.cum_quarantine
        totals = {
            "lines_total": lane.total_lines,
            "lines_matched": lane.total_parsed,
            "lines_skipped": lane.total_skipped,
            "chunks": lane.total_chunks,
            "tenant": lane.name,
            "window": {
                "cumulative_windows": lane.windows_published + 1,
                "reloads": lane.reloads,
                **(
                    {"incomplete": {
                        "windows": list(lane.cum_incomplete_windows),
                        "reasons": list(lane.cum_incomplete_reasons),
                    }}
                    if lane.cum_incomplete_windows
                    else {}
                ),
            },
        }
        qt = _quarantine_totals(q)
        if qt:
            totals["quarantine"] = qt
        return pipeline.finalize(
            pipeline.AnalysisState(**lane.cum_arrays), lane.packed, self.cfg,
            lane.cum_tracker, topk=self.topk, totals=totals, v6_digests={},
        )

    def window_report(self, tenant: str, wid: int) -> dict | None:
        lane = self.lanes.get(tenant)
        if lane is None:
            return None
        with self._pub_lock:
            return lane.window_reports.get(wid)

    def published(self, tenant: str, name: str) -> dict | None:
        lane = self.lanes.get(tenant)
        if lane is None:
            return None
        with self._pub_lock:
            return lane.published.get(name)

    # -- hot reload (one tenant; others untouched) -------------------------
    def _maybe_reload(self) -> None:
        if not self._reload_req.is_set():
            return
        self._reload_req.clear()
        with self._reload_lock:
            names = list(self._reload_names) or sorted(self.lanes)
            self._reload_names.clear()
        for name in names:
            lane = self.lanes.get(name)
            if lane is None:
                continue
            with obs.span("serve.reload", tenant=name):
                try:
                    self._do_reload(lane)
                except _ReloadFlushError as e:
                    raise e.__cause__
                except (AnalysisError, ValueError, OSError) as e:
                    # atomic PER TENANT: this lane keeps its old tensor
                    # and counters; every other lane never even sees it
                    lane.reload_errors += 1
                    lane.last_reload_error = str(e)
                    self.reload_errors += 1
                    obs.instant("serve.reload.failed", args={
                        "tenant": name, "error": str(e)[:200],
                    })

    def _do_reload(self, lane: _Lane) -> None:
        old_packed = lane.packed
        new_packed = pack_mod.load_packed(lane.spec.ruleset)
        # fault site FIRST (serve.py discipline): a reload dying mid-swap
        # leaves this tenant — and trivially all others — intact
        faults.fire("reload.midbatch")
        mig = build_migration(old_packed, new_packed, tenant=lane.name)
        sa_new = dur_new = None
        if self.scfg.static_analysis:
            # re-verdict ONLY this tenant (signature reuse against its
            # own previous run); an analyze failure aborts THIS reload
            sa_new, dur_new = self._compute_static(new_packed, reuse=lane.sa)
        # flush ONLY this lane's inflight tail through the OLD ruleset —
        # no other tenant's batcher or window clock is touched
        try:
            self._flush_inflight(lane)
        except Exception as e:
            raise _ReloadFlushError() from e
        from .stream import LineBatcher

        old_packer = lane.batcher.packer
        packer = pack_mod.LinePacker(new_packed)
        packer.parsed, packer.skipped = old_packer.parsed, old_packer.skipped
        batcher = LineBatcher(packer, False, [], {}, self.batch_size)
        live_arrays = None
        q: dict[tuple, int] = {}
        if not mig.identity:
            live_arrays, q = migrate_arrays(
                self.engine.host_arrays(lane.name), mig, old_packed, self.cfg
            )
        sa_obj_new = (
            sa_new.to_obj(new_packed) if sa_new is not None else None
        )
        # ONE publish-locked swap for THIS lane: ring epochs, cumulative
        # image, live slice, rule tensor, batcher, and static verdicts
        # move together (an HTTP render never pairs old with new)
        with self._pub_lock:
            if not mig.identity:
                _merge_quarantine(lane.win_quarantine, q)
                for ep in lane.ring.epochs:
                    ep_arrays, ep_q = migrate_arrays(
                        ep.arrays, mig, old_packed, self.cfg
                    )
                    ep.arrays = ep_arrays
                    _merge_quarantine(ep.quarantine, ep_q)
                    ep.meta["migrated"] = ep.meta.get("migrated", 0) + 1
                    new_tables, dropped = migrate_tracker_tables(
                        ep.tracker_tables, mig
                    )
                    ep.tracker_tables = new_tables
                    lane.talker_entries_dropped += dropped
                lane.cum_arrays, cq = migrate_arrays(
                    lane.cum_arrays, mig, old_packed, self.cfg
                )
                _merge_quarantine(lane.cum_quarantine, cq)
                cum_tables, cdrop = migrate_tracker_tables(
                    lane.cum_tracker.tables(), mig
                )
                lane.talker_entries_dropped += cdrop
                lane.cum_tracker = TopKTracker(self.cfg.sketch.topk_capacity)
                for acl, table in cum_tables.items():
                    for src, est in table.items():
                        lane.cum_tracker.offer(acl, src, est)
                win_tables, wdrop = migrate_tracker_tables(
                    lane.tracker.tables(), mig
                )
                lane.talker_entries_dropped += wdrop
                lane.tracker = TopKTracker(self.cfg.sketch.topk_capacity)
                for acl, table in win_tables.items():
                    for src, est in table.items():
                        lane.tracker.offer(acl, src, est)
            # the engine swap: same rung = one slice of a traced arg
            # (no recompile anywhere); rung change = bucket move (only
            # the destination bucket's step may compile)
            self.engine.reload_tenant(lane.name, new_packed)
            if not mig.identity:
                self.engine.set_arrays(lane.name, live_arrays)
            lane.packed = new_packed
            lane.batcher = batcher
            if lane.store is not None:
                if not mig.identity:
                    lane.store.mark_era(lane.win_id, lane.reloads + 1)
                lane.store.set_labels([
                    (m.firewall, m.acl, m.index)
                    for m in new_packed.key_meta
                ])
            if sa_new is not None:
                self._install_static(lane, sa_new, sa_obj_new, dur_new)
        if sa_new is not None:
            self._write_json(lane.name, "static.json", sa_obj_new)
        lane.reloads += 1
        lane.win_reloads += 1
        self.reloads += 1
        flightrec.cursor(tenant=lane.name, reloads=self.reloads)
        obs.instant("serve.reload.ok", args={
            "tenant": lane.name,
            "n_keys": new_packed.n_keys,
            "migrated": not mig.identity,
        })

    # -- health / metrics --------------------------------------------------
    def health(self) -> dict:
        q = self.queue.snapshot()
        stalled = len(self.listeners.stalled(self.cfg.stall_timeout_sec))
        deg_subsystems = self.degraded_set()
        with self._deg_lock:
            deg_errors = dict(self.degraded)
        degraded = (
            q["dropped"] > 0
            or self.reload_errors > 0
            or stalled > 0
            or self.listeners.alive() < len(self.listeners.listeners)
            or bool(deg_subsystems)
        )
        return {
            "status": "degraded" if degraded else "ok",
            "degraded_subsystems": deg_subsystems,
            **({"degraded_errors": deg_errors} if deg_errors else {}),
            "degraded_events": self.degraded_events,
            "recovered_events": self.recovered_events,
            "uptime_sec": round(time.time() - self._t0, 3),
            "windows_published": self.windows_published,
            "lines_total": self.total_lines,
            "lines_unrouted": self.lines_unrouted_total,
            "queue": q,
            "listeners": {
                "n": len(self.listeners.listeners),
                "alive": self.listeners.alive(),
                "stalled": stalled,
                "addresses": self.listeners.addresses(),
            },
            "reloads": self.reloads,
            "reload_errors": self.reload_errors,
            "window": {
                "mode": "lines" if self.scfg.window_lines else "sec",
                "length": self.scfg.window_lines or self.scfg.window_sec,
                "ring": self.scfg.ring,
            },
            "world": self.world,
            "tenants": {
                name: {
                    "current_window": {
                        "id": lane.win_id,
                        "pushed": getattr(lane, "win_pushed", 0),
                    },
                    "windows_published": lane.windows_published,
                    "lines_total": lane.total_lines,
                    "routed_total": lane.routed_total,
                    "reloads": lane.reloads,
                    "reload_errors": lane.reload_errors,
                    **(
                        {"last_reload_error": lane.last_reload_error}
                        if lane.last_reload_error
                        else {}
                    ),
                    "ruleset": {
                        "n_rules": lane.packed.n_rules,
                        "n_acls": lane.packed.n_acls,
                        "n_keys": lane.packed.n_keys,
                    },
                }
                for name, lane in sorted(self.lanes.items())
            },
        }

    def tenants_obj(self) -> dict:
        """The /tenants endpoint: the engine's packing-registry image
        plus per-lane service counters."""
        return {
            "engine": self.engine.describe(),
            "routing": {
                "default": self.router.default,
                "unrouted_total": self.lines_unrouted_total,
            },
            "fairness": self.fairness(),
        }

    def fairness(self) -> dict:
        """Who filled the shared queue: per-tenant consumed shares.

        The accounting HALF of fairness — the bound queue is the
        mechanism; these counters make a noisy tenant visible before it
        silently starves the ring (ISSUE 16)."""
        total = max(self.lines_consumed_total, 1)
        shares = {
            name: round(lane.routed_total / total, 4)
            for name, lane in sorted(self.lanes.items())
        }
        return {
            "lines_consumed_total": self.lines_consumed_total,
            "lines_unrouted_total": self.lines_unrouted_total,
            "shares": shares,
            "max_share": max(shares.values()) if shares else 0.0,
            "min_share": min(shares.values()) if shares else 0.0,
        }

    def _sample_metrics(self) -> dict:
        return {
            **self.listeners.sample_metrics(),
            "windows_published": self.windows_published,
            "reloads": self.reloads,
            "lines_total": self.total_lines,
        }

    def per_tenant_gauges(self) -> dict[str, dict]:
        """Numeric gauges per tenant — ONE source for the JSON
        ``/metrics`` `tenants` block and the Prometheus
        ``{tenant="..."}`` labeled series (``render_prom_labeled``)."""
        fairness = self.fairness()
        out = {}
        for name, lane in sorted(self.lanes.items()):
            g = {
                "lines_routed_total": lane.routed_total,
                "lines_windowed_total": lane.total_lines,
                "windows_published": lane.windows_published,
                "reloads_total": lane.reloads,
                "reload_errors_total": lane.reload_errors,
                "queue_share": fairness["shares"].get(name, 0.0),
            }
            g.update(lane.lat_cum.gauges("latency_ingest_to_publish_"))
            if lane.store is not None:
                g.update(lane.store.gauges())
            out[name] = g
        return out

    def metrics_gauges(self) -> dict:
        q = self.queue.snapshot()
        g = {
            "queue_depth": q["depth"],
            "queue_capacity": q["capacity"],
            "lines_received_total": q["received"],
            "drops_total": q["dropped"],
            "lines_consumed_total": self.lines_consumed_total,
            "lines_unrouted_total": self.lines_unrouted_total,
            "lines_windowed_total": self.total_lines,
            "windows_published": self.windows_published,
            "reloads_total": self.reloads,
            "reload_errors_total": self.reload_errors,
            "listeners_alive": self.listeners.alive(),
            "tenants_hosted": len(self.lanes),
            "world": self.world,
            "degraded_subsystems": len(self.degraded_set()),
            "degraded_events_total": self.degraded_events,
            "recovered_events_total": self.recovered_events,
            "fairness_max_share": self.fairness()["max_share"],
        }
        g.update(self.lat_cum.gauges("latency_ingest_to_publish_"))
        g.update(retrypolicy.gauges())
        if self.wal is not None:
            w = self.wal.stats()
            g.update({
                "wal_appended_total": w["appended"],
                "wal_segments": w["segments"],
                "wal_bytes": w["bytes"],
                "wal_evicted_records_total": w["evicted_records"],
            })
        if self.scfg.lineage:
            g["lineage_records_total"] = self.lineage_records_total
            g["trend_events_total"] = self.trend_events_total
        stores = [
            lane.store for lane in self.lanes.values()
            if lane.store is not None
        ]
        if stores:
            # service-level rollup; per-tenant detail rides the labeled
            # ``per_tenant_gauges`` series
            g["epochstore_spilled_total"] = sum(
                s.spilled_total for s in stores
            )
            g["epochstore_epochs"] = sum(
                s.stats()["epochs"] for s in stores
            )
            g["epochstore_bytes"] = sum(
                s.stats()["bytes"] for s in stores
            )
        if self.slo is not None:
            g.update(self.slo.gauges())
        g.update(devprof.gauges())
        g.update(devprof.device_memory_gauges())
        return g

    def build_info_dict(self) -> dict:
        """Static build identity for ``ra_build_info`` (tenancy tier)."""
        return build_info({
            "mesh": f"{self.cfg.mesh_shape}/{max(self.world, 1)}",
        })

    def render_prom_all(self) -> str:
        """The full Prometheus exposition: service gauges, per-tenant
        labeled gauges, the aggregate latency histogram, and one labeled
        histogram per tenant — every series derives from the same counts
        the JSON endpoint serves (drift-checked by verify/registry.py)."""
        parts = [
            render_build_info_prom(self.build_info_dict()),
            render_prom(self.metrics_gauges(), prefix="ra_serve_"),
            render_prom_labeled(
                self.per_tenant_gauges(), prefix="ra_serve_tenant_",
                label="tenant",
            ),
            self.lat_cum.render_prom("ra_serve_ingest_to_publish_seconds"),
        ]
        if self.slo is not None:
            parts.append(render_prom_labeled(
                self.slo.labeled_gauges(),
                prefix="ra_serve_", label="objective",
            ))
        for name, lane in sorted(self.lanes.items()):
            parts.append(lane.lat_cum.render_prom(
                "ra_serve_tenant_ingest_to_publish_seconds",
                labels={"tenant": name},
            ))
        return "".join(parts)

    # -- service plumbing --------------------------------------------------
    def _write_json(self, tenant: str, name: str, obj: dict) -> None:
        """Publish one JSON artifact (under ``serve_dir/t/<tenant>/``
        when a tenant is named) with serve.py's degraded-publisher
        semantics."""
        d = (
            os.path.join(self.scfg.serve_dir, "t", tenant)
            if tenant
            else self.scfg.serve_dir
        )
        path = os.path.join(d, name)
        tmp = path + ".tmp"

        def _write():
            faults.fire("serve.publish.fail")
            os.makedirs(d, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(obj, f, indent=2)
            os.replace(tmp, path)

        try:
            retrypolicy.call("serve.publish", _write)
        except (OSError, AnalysisError) as e:
            self._degrade("publisher", e)
            return
        self._recover("publisher")

    def _start_http(self) -> None:
        if self._http is None:
            return
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, name="ra-serve-http", daemon=True
        )
        self._http_thread.start()

    def _start_watcher(self) -> None:
        if not self.scfg.reload_watch:
            return

        def mtimes(lane: _Lane) -> tuple:
            out = []
            for suffix in (".npz", ".json"):
                try:
                    st = os.stat(lane.spec.ruleset + suffix)
                    out.append((st.st_mtime_ns, st.st_size))
                except OSError:
                    out.append(None)
            return tuple(out)

        def watch():
            # serve.py's debounced pair-watch, per tenant: each tenant's
            # stable mtime change queues a reload of THAT tenant only
            last = {n: mtimes(l) for n, l in self.lanes.items()}
            pending: dict[str, tuple | None] = {}
            while not self._stop_req.wait(self.scfg.reload_poll_sec):
                for name, lane in self.lanes.items():
                    cur = mtimes(lane)
                    if cur == last[name]:
                        pending[name] = None
                        continue
                    if any(m is None for m in cur):
                        continue
                    if cur == pending.get(name):
                        last[name] = cur
                        pending[name] = None
                        self.request_reload(name)
                    else:
                        pending[name] = cur

        self._watch_thread = threading.Thread(
            target=watch, name="ra-serve-reload-watch", daemon=True
        )
        self._watch_thread.start()

    def _install_signals(self) -> None:
        import signal

        if threading.current_thread() is not threading.main_thread():
            return
        wanted = {
            getattr(signal, "SIGHUP", None): lambda *_: self.request_reload(),
            signal.SIGINT: lambda *_: self._stop_req.set(),
            signal.SIGTERM: lambda *_: self._stop_req.set(),
        }
        for sig, handler in wanted.items():
            if sig is None:
                continue
            try:
                self._old_signals[sig] = signal.signal(sig, handler)
            except (ValueError, OSError):
                pass

    def _teardown(self, aborted: BaseException | None) -> None:
        import signal

        self._stop_req.set()
        for sig, old in self._old_signals.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old_signals = {}
        if self._http is not None:
            if self._http_thread is not None:
                self._http.shutdown()
                self._http.server_close()
                self._http_thread.join(timeout=5.0)
            else:
                self._http.server_close()
        self.listeners.close()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=5.0)
        if self.wal is not None:
            self.wal.close()
        for lane in self.lanes.values():
            if lane.store is not None:
                lane.store.sync()
                lane.store.close()
        if self._lineage_log is not None:
            self._lineage_log.sync()
            self._lineage_log.close()
            self._lineage_log = None
        obs.unregister_sampler("listener")
        obs.unregister_sampler("serve")

    # -- the run loop ------------------------------------------------------
    def _route(self, line: str, tag: str | None) -> tuple[str | None, str]:
        tenant, body = self.router.route(line, tag)
        if tenant is None or tenant not in self.lanes:
            self.lines_unrouted_total += 1
            return None, body
        return tenant, body

    def _loop(self) -> None:
        scfg = self.scfg
        t0 = time.monotonic()
        while True:
            if self._stop_req.is_set():
                break
            if scfg.stop_after_sec and time.monotonic() - t0 >= scfg.stop_after_sec:
                break
            self._maybe_reload()
            self._check_metrics_health()
            if scfg.window_sec:
                # per-lane wall clocks: one lane's slow rotation (or
                # reload) delays only its own cadence, never another's
                now = time.monotonic()
                for name in sorted(self.lanes):
                    lane = self.lanes[name]
                    if lane.next_rotation is not None and now >= lane.next_rotation:
                        self._rotate(lane)
                        lane.next_rotation += scfg.window_sec
                        now2 = time.monotonic()
                        while lane.next_rotation <= now2:
                            lane.next_rotation += scfg.window_sec
                if scfg.max_windows and self.windows_published >= scfg.max_windows:
                    break
            got = self.queue.pop_tagged(timeout=0.1)
            if got is not None:
                line, t_recv, tag = got
                tenant, body = self._route(line, tag)
                if tenant is None:
                    continue
                lane = self.lanes[tenant]
                if self.wal is not None:
                    # durably spool WITH the tenant key (record v2),
                    # BEFORE window accounting (serve.py discipline);
                    # the cursor feeds the lineage records' WAL range
                    self._wal_next = self.wal.append(body, tenant=tenant) + 1
                for ev in lane.batcher.push(body):
                    self._consume_event(lane, ev)
                self._note_receipt(lane, t_recv)
                lane.win_pushed += 1
                lane.routed_total += 1
                self.lines_consumed_total += 1
                if scfg.window_lines and lane.win_pushed >= scfg.window_lines:
                    self._rotate(lane)
                    if scfg.max_windows and self.windows_published >= scfg.max_windows:
                        break
                continue
            # idle tick: listener liveness + wedge watchdog (shared tier)
            if self.listeners.alive() == 0 and len(self.queue) == 0:
                err = self.listeners.first_error()
                if err is not None:
                    raise FeedWorkerError(
                        f"every serve listener died; first error: "
                        f"{type(err).__name__}: {err}"
                    ) from err
                break
            stalled = self.listeners.stalled(self.cfg.stall_timeout_sec)
            if stalled:
                for lane in self.lanes.values():
                    lane._win_saw_stall = True
                if len(stalled) == self.listeners.alive() and len(self.queue) == 0:
                    names = ", ".join(ln.label for ln in stalled)
                    raise StallError(
                        f"every live serve listener stalled (no heartbeat "
                        f"for {self.cfg.stall_timeout_sec:g}s): {names}"
                    )
        # bounded shutdown: stop ingress, count the backlog as drops,
        # publish every lane's final partial window
        self.listeners.close()
        undelivered = self.queue.discard_remaining()
        for name in sorted(self.lanes):
            lane = self.lanes[name]
            if (
                lane.win_pushed
                or lane.batcher.raw
                or lane.pending
                or lane.win_lines
                or undelivered
            ):
                self._rotate(lane, partial=True)


# ---------------------------------------------------------------------------
# HTTP endpoint (per-tenant routes under /t/<name>/...).
# ---------------------------------------------------------------------------


def _make_tenant_http_handler():
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        server_version = "ra-serve-tenants/1"

        def log_message(self, *a):  # silence per-request stderr noise
            pass

        def _send(self, code: int, obj) -> None:
            body = json.dumps(obj, indent=2).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, text: str, ctype: str) -> None:
            body = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 (http.server API)
            drv: TenantServeDriver = self.server.driver
            raw_path, _, query = self.path.partition("?")
            path = raw_path.rstrip("/") or "/"
            try:
                if path == "/health":
                    return self._send(200, drv.health())
                if path == "/metrics":
                    if "format=prom" in query:
                        return self._send_text(
                            200, drv.render_prom_all(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    return self._send(200, {
                        **drv._sample_metrics(),
                        **drv.metrics_gauges(),
                        "tenants": drv.per_tenant_gauges(),
                        "fairness": drv.fairness(),
                        "build_info": drv.build_info_dict(),
                    })
                if path == "/tenants":
                    return self._send(200, drv.tenants_obj())
                if path == "/lineage":
                    if not drv.scfg.lineage:
                        return self._send(404, {
                            "error": "lineage disabled (--lineage off)",
                        })
                    return self._send(200, drv.lineage_tail())
                if path.startswith("/t/"):
                    parts = path.split("/")  # /t/<name>/report[...]
                    name = parts[2] if len(parts) > 2 else ""
                    if name not in drv.lanes:
                        return self._send(404, {
                            "error": f"unknown tenant {name!r}",
                            "tenants": sorted(drv.lanes),
                        })
                    sub = "/".join(parts[3:])
                    if sub == "report":
                        obj = drv.published(name, "report")
                        return self._send(200, obj) if obj else self._send(
                            404, {"error": "no window published yet"}
                        )
                    if sub == "report/cumulative":
                        obj = drv.published(name, "cumulative")
                        return self._send(200, obj) if obj else self._send(
                            404, {"error": "no window published yet"}
                        )
                    if sub == "report/static":
                        obj = drv.published(name, "static")
                        return self._send(200, obj) if obj else self._send(
                            404,
                            {"error": "static analysis disabled "
                                      "(serve --static-analysis) or not yet run"},
                        )
                    if sub == "diff":
                        obj = drv.published(name, "diff")
                        return self._send(200, obj) if obj else self._send(
                            404, {"error": "fewer than two windows published"}
                        )
                    if sub.startswith("report/window/"):
                        try:
                            wid = int(sub.rsplit("/", 1)[1])
                        except ValueError:
                            return self._send(400, {"error": "bad window id"})
                        obj = drv.window_report(name, wid)
                        return self._send(200, obj) if obj else self._send(
                            404, {"error": f"window {wid} not in the ring"}
                        )
                    if sub == "lineage":
                        if not drv.scfg.lineage:
                            return self._send(404, {
                                "error": "lineage disabled (--lineage off)",
                            })
                        lane = drv.lanes[name]
                        with drv._pub_lock:
                            recs = [
                                lane.lineage_recent[w]
                                for w in sorted(lane.lineage_recent)
                            ]
                        return self._send(200, {"records": recs})
                return self._send(404, {
                    "error": "unknown path",
                    "endpoints": [
                        "/health", "/metrics", "/tenants", "/lineage",
                        "/t/<name>/report", "/t/<name>/report/cumulative",
                        "/t/<name>/report/static",
                        "/t/<name>/report/window/<id>", "/t/<name>/diff",
                        "/t/<name>/lineage",
                    ],
                })
            except BrokenPipeError:
                pass

    return Handler


def _make_tenant_http_server(addr, driver):
    from http.server import ThreadingHTTPServer

    srv = ThreadingHTTPServer(addr, _make_tenant_http_handler())
    srv.daemon_threads = True
    srv.driver = driver
    return srv
