"""Unified tracing + metrics plane (the observability subsystem).

The reference's only visibility was Hadoop job counters and stdout
(SURVEY §6); after the pipelined ingest (PR 2) and chaos tiers (PR 3)
this repo runs a multi-threaded, multi-process pipeline whose behavior
was explained only by end-of-run totals.  This module is the Dapper-
style answer: one low-overhead plane that records *where time goes*
across parse/pack/H2D/step/checkpoint and *what every worker was doing*
at any fault or re-formation.

Three pieces, one arming discipline (the ``faults.py`` pattern — the
disarmed cost of every site is a single module-global ``None`` check,
verified by ``bench_suite.py obs``):

- **Span tracer.**  :func:`complete`/:func:`span`/:func:`instant` record
  Chrome trace-event spans (loads in Perfetto / ``chrome://tracing``).
  Each process appends to its own ``trace-<pid>.jsonl`` shard in the
  trace directory — newline-delimited complete events, flushed per
  event, so a worker that dies mid-run (even ``os._exit`` crash faults)
  leaves a well-formed shard containing everything it finished.  Only
  COMPLETE ("X") and instant ("i") events are ever written, so a merged
  trace can never hold an orphan open span.

- **Cross-process capture.**  :func:`start_trace` exports the directory
  to :data:`ENV_VAR`; spawned children (feeder worker processes, elastic
  generation workers) inherit it and lazily arm on their first span —
  the same inheritance discipline as ``RA_FAULT_PLAN``.  The parent
  merges every shard into ONE timeline (:func:`merge_trace`) at
  shutdown, including after typed aborts; timestamps are epoch
  microseconds so shards from different processes share a clock.

- **Metrics snapshotter.**  :func:`start_metrics` appends JSON-lines
  records to a file every N seconds from a daemon thread: wall clock,
  cumulative/instantaneous lines/s (fed by ``ThroughputMeter.tick`` via
  :func:`add_lines`), RSS, plus whatever samplers live components
  registered (:func:`register_sampler`) — PrefetchingSource queue depth
  and producer/consumer wait time, feeder pool occupancy, elastic
  recovery totals — and event records pushed by components
  (:func:`metric_event`: checkpoint bytes/latency, periodic throughput
  lines).  A 1e8-line sustained run is watchable by tailing the file;
  no stderr scraping.

Lifecycle: the CLI arms from ``--trace-out`` / ``--metrics-out`` and
calls :func:`shutdown` in a ``finally`` so the merged trace and the
final metrics record exist even when the run ends in a typed abort.
Library callers use the same module functions directly.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time

#: Environment variable carrying the trace directory to child processes
#: (feeder workers, elastic generation workers) — the RA_FAULT_PLAN
#: inheritance discipline.
ENV_VAR = "RA_TRACE_DIR"

#: Default cadence of the metrics snapshotter (seconds).
DEFAULT_METRICS_EVERY = 10.0

#: Waits shorter than this never become backpressure/starved spans —
#: a healthy pipeline's sub-millisecond queue handoffs are not stalls.
STALL_SPAN_MIN_SEC = 0.001

#: Backstop age for pruning leftover shards whose writer PID appears
#: alive (PID recycled by an unrelated long-lived process): older than
#: this, the shard is a previous run's regardless.  Deliberately far
#: above any realistic launcher stagger — wrongly unlinking a live
#: sibling's shard loses its telemetry for the whole run, while keeping
#: a recycled-PID leftover only cosmetically pads one merge.
STALE_SHARD_SEC = 3600.0


class Tracer:
    """One process's span shard: ``trace-<pid>.jsonl`` in the trace dir.

    Events are Chrome trace-event objects, one JSON per line, flushed as
    written — append-only and crash-tolerant by construction (a process
    killed mid-write loses at most its final partial line, which
    :func:`merge_trace` skips).  Timestamps are epoch microseconds
    (derived from one ``time.time``/``perf_counter`` pairing at arm
    time) so shards from different processes merge onto one axis.
    """

    def __init__(self, trace_dir: str, role: str = ""):
        os.makedirs(trace_dir, exist_ok=True)
        self.dir = os.path.abspath(trace_dir)
        self.pid = os.getpid()
        self.path = os.path.join(self.dir, f"trace-{self.pid}.jsonl")
        self._f = open(self.path, "a", encoding="utf-8")
        self._wlock = threading.Lock()
        # one pairing converts perf_counter spans to the shared epoch axis
        self._epoch_us = time.time_ns() // 1_000
        self._pc0 = time.perf_counter()
        self.set_role(role or f"pid-{self.pid}")

    def _us(self, pc: float) -> int:
        return self._epoch_us + int((pc - self._pc0) * 1e6)

    def _emit(self, ev: dict) -> None:
        line = json.dumps(ev, separators=(",", ":"))
        with self._wlock:
            f = self._f
            if f.closed:
                return
            f.write(line + "\n")
            f.flush()

    def set_role(self, role: str) -> None:
        """Name this process's track in the merged timeline."""
        self._emit(
            {
                "ph": "M",
                "name": "process_name",
                "pid": self.pid,
                "tid": 0,
                "args": {"name": f"{role} (pid {self.pid})"},
            }
        )

    def complete(
        self,
        name: str,
        t0_pc: float,
        t1_pc: float,
        cat: str = "",
        args: dict | None = None,
    ) -> None:
        """One finished span, endpoints in ``time.perf_counter`` units."""
        ev = {
            "ph": "X",
            "name": name,
            "cat": cat or name.split(".", 1)[0],
            "pid": self.pid,
            "tid": threading.get_native_id(),
            "ts": self._us(t0_pc),
            "dur": max(0, int((t1_pc - t0_pc) * 1e6)),
        }
        if args:
            ev["args"] = args
        self._emit(ev)

    def instant(self, name: str, args: dict | None = None) -> None:
        ev = {
            "ph": "i",
            "s": "p",  # process-scoped marker line
            "name": name,
            "cat": name.split(".", 1)[0],
            "pid": self.pid,
            "tid": threading.get_native_id(),
            "ts": self._us(time.perf_counter()),
        }
        if args:
            ev["args"] = args
        self._emit(ev)

    def close(self) -> None:
        with self._wlock:
            if not self._f.closed:
                self._f.close()


class MetricsPlane:
    """Periodic JSONL snapshots + pushed events, appended to one file.

    Snapshot records (``kind="snapshot"``) carry the built-in gauges
    (lines, rates, RSS, uptime) plus one key per registered sampler;
    event records (``kind=<event kind>``) land immediately when a
    component pushes one.  The sampling thread is a daemon named
    ``ra-metrics`` and is joined by :meth:`close` (the conftest leak
    audit counts it).  A sampler that raises is dropped from that
    snapshot only — observability must never kill the run it observes.
    """

    def __init__(self, path: str, every_sec: float = DEFAULT_METRICS_EVERY):
        self.path = os.path.abspath(path)
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(self.path, "a", encoding="utf-8")
        self.every = max(0.05, float(every_sec))
        self._lock = threading.Lock()
        self._lines = 0
        # interval/rate derivations run on the MONOTONIC clock: the
        # snapshot's "t" stamp stays wall-clock for correlation, but an
        # NTP step must never produce a negative or inflated lines/s
        # line in the JSONL (ISSUE 15 satellite)
        self._t0 = time.monotonic()
        self._last_t = self._t0
        self._last_lines = 0
        self._stop = threading.Event()
        # tick-failure accounting (DESIGN §19 degraded mode): a failing
        # snapshot — unwritable file, injected metrics.snapshot.fail —
        # must never kill the ra-metrics thread; it is counted, the next
        # tick retries naturally, and serve marks the metrics subsystem
        # degraded until a tick succeeds again
        self.errors = 0
        self.consec_errors = 0
        self.last_error = ""
        self._thread = threading.Thread(
            target=self._loop, name="ra-metrics", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        from . import faults

        while not self._stop.wait(self.every):
            try:
                faults.fire("metrics.snapshot.fail")
                self.snapshot()
            except Exception as e:
                with self._lock:
                    self.errors += 1
                    self.consec_errors += 1
                    self.last_error = f"{type(e).__name__}: {e}"[:200]
            else:
                with self._lock:
                    self.consec_errors = 0

    def health(self) -> dict:
        with self._lock:
            return {
                "alive": self._thread.is_alive(),
                "errors": self.errors,
                "consec_errors": self.consec_errors,
                "last_error": self.last_error,
            }

    def add_lines(self, n: int) -> None:
        with self._lock:
            self._lines += n

    def register(self, name: str, fn) -> None:
        _register_sampler_impl(name, fn)

    def unregister(self, name: str) -> None:
        _unregister_sampler_impl(name)

    def _write(self, rec: dict) -> None:
        line = json.dumps(rec, separators=(",", ":"))
        with self._lock:
            f = self._f
            if f.closed:
                return
            f.write(line + "\n")
            f.flush()
        # the flight recorder keeps the most recent snapshots in its ring
        # so a crash dump carries the last gauge readings (DESIGN §20)
        fr = _flight
        if fr is not None:
            fr.instant(f"metrics.{rec.get('kind', 'snapshot')}", rec)

    def event(self, kind: str, fields: dict) -> None:
        self._write({"kind": kind, "t": round(time.time(), 3), **fields})

    def snapshot(self, kind: str = "snapshot") -> dict:
        now = time.monotonic()
        with self._lock:
            lines = self._lines
            dt_inst = now - self._last_t
            d_lines = lines - self._last_lines
            self._last_t, self._last_lines = now, lines
        with _samplers_lock:
            samplers = list(_samplers.items())
        rec = {
            "kind": kind,
            "t": round(time.time(), 3),
            "uptime_sec": round(now - self._t0, 3),
            "lines": lines,
            "lines_per_sec_inst": round(d_lines / dt_inst, 1) if dt_inst > 0 else 0.0,
            "lines_per_sec_cum": (
                round(lines / (now - self._t0), 1) if now > self._t0 else 0.0
            ),
            "rss_bytes": _rss_bytes(),
        }
        for name, fn in samplers:
            try:
                rec[name] = fn()
            except Exception:
                pass  # a broken sampler must never take the run down
        self._write(rec)
        return rec

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10.0)
        self.snapshot(kind="final")
        with self._lock:
            if not self._f.closed:
                self._f.close()


def _rss_bytes() -> int:
    """Resident set size; /proc on Linux, getrusage elsewhere."""
    try:
        with open("/proc/self/statm", "r", encoding="ascii") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        try:
            import resource
            import sys as _sys

            peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # this branch only runs where /proc is absent; macOS reports
            # ru_maxrss in BYTES (Linux's KiB never reaches here) — and
            # it is a peak, the closest available stand-in for RSS
            return int(peak) if _sys.platform == "darwin" else int(peak) * 1024
        except Exception:
            return 0


# ---------------------------------------------------------------------------
# Module arming state — the faults.py discipline: `_tracer is None` /
# `_metrics is None` are the production fast paths; the env check runs at
# most once per process so spawned children (which inherit RA_TRACE_DIR)
# arm themselves lazily on their first span.
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_tracer: Tracer | None = None
_metrics: MetricsPlane | None = None
_env_checked = False
_env_exported = False
_role = ""

#: Flight-recorder tap (runtime/flightrec.py): when the always-on black
#: box is armed, every span/instant also lands in its in-memory ring —
#: NO file I/O, strictly cheaper than the armed trace plane.  Disarmed
#: cost: one module-global None check per event.
_flight = None

#: Module-level sampler registry.  Registration is independent of the
#: metrics plane's arming so (a) components register once and both the
#: JSONL snapshotter AND a flight-recorder crash dump read live gauges,
#: and (b) a sampler registered before --metrics-out arms still lands in
#: the first snapshot.  Callers unregister at component teardown.
_samplers: dict[str, object] = {}
_samplers_lock = threading.Lock()


def _set_flight(rec) -> None:
    """Install (or clear) the flight-recorder ring tap (flightrec.arm)."""
    global _flight
    _flight = rec


def _register_sampler_impl(name: str, fn) -> None:
    with _samplers_lock:
        _samplers[name] = fn


def _unregister_sampler_impl(name: str) -> None:
    with _samplers_lock:
        _samplers.pop(name, None)


def sampler_snapshot() -> dict:
    """One guarded reading of every live sampler (flight-recorder dumps)."""
    with _samplers_lock:
        items = list(_samplers.items())
    out: dict = {}
    for name, fn in items:
        try:
            out[name] = fn()
        except Exception as e:  # a broken gauge must not block forensics
            out[name] = f"<sampler error: {type(e).__name__}: {e}>"
    return out


def start_trace(trace_dir: str, *, role: str = "main", export_env: bool = True) -> Tracer:
    """Arm span tracing process-wide, writing this process's shard.

    ``export_env`` publishes the directory to :data:`ENV_VAR` so worker
    processes spawned while armed write sibling shards.
    """
    global _tracer, _env_checked, _env_exported
    with _lock:
        if _tracer is not None:
            _tracer.close()
        if export_env:
            # this process OWNS the run: prune leftovers of previous
            # runs (stale shards + the old merged file) so the merge
            # covers exactly this run.  Lazy-armed children and
            # explicit export_env=False callers never prune — they may
            # be joining a directory other live processes are writing.
            _prune_stale(trace_dir)
        _tracer = Tracer(trace_dir, role=role)
        _env_checked = True
        if export_env:
            os.environ[ENV_VAR] = _tracer.dir
            _env_exported = True
        return _tracer


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True  # exists but not ours — treat as alive
    return True


def _prune_stale(trace_dir: str) -> None:
    """Remove leftovers of PREVIOUS runs so the merge covers this one.

    A shard belongs to a previous run exactly when its writer process is
    gone — shard names carry the writer PID, so a liveness probe tells a
    dead run's leftovers (pruned, even seconds after an abort-and-retry)
    from a live sibling rank's shard in a shared multi-launcher
    directory (kept: unlinking it would strand the sibling's events on
    an unlinked inode).  The mtime backstop catches the rare recycled
    PID that probes alive.
    """
    now = time.time()
    me = os.getpid()
    for path in glob.glob(os.path.join(trace_dir, "trace-*.jsonl")):
        name = os.path.basename(path)
        try:
            pid = int(name[len("trace-"):-len(".jsonl")])
        except ValueError:
            continue
        try:
            # our own prior shard is always a previous run's (the old
            # tracer is closed before pruning); others prune when dead
            if pid == me or not _pid_alive(pid) or (
                now - os.path.getmtime(path) > STALE_SHARD_SEC
            ):
                os.unlink(path)
        except OSError:
            continue
    try:
        os.unlink(os.path.join(trace_dir, "trace.json"))
    except OSError:
        pass


def start_metrics(path: str, every_sec: float = DEFAULT_METRICS_EVERY) -> MetricsPlane:
    """Arm the metrics snapshotter (parent-process only, no env export)."""
    global _metrics
    with _lock:
        if _metrics is not None:
            _metrics.close()
        _metrics = MetricsPlane(path, every_sec)
        return _metrics


def shutdown(*, merge: bool = True) -> str | None:
    """Disarm everything; merge trace shards when this process owns them.

    Returns the merged trace path (or None when tracing was not armed).
    Safe to call twice and from a ``finally`` after a typed abort — that
    is exactly when a trace is most valuable.
    """
    global _tracer, _metrics, _env_checked, _env_exported
    with _lock:
        tr, mp = _tracer, _metrics
        _tracer, _metrics = None, None
        exported = _env_exported
        _env_exported = False
        _env_checked = True
    if mp is not None:
        mp.close()
    merged = None
    if tr is not None:
        tr.close()
        if exported:
            os.environ.pop(ENV_VAR, None)
        if merge:
            merged = merge_trace(tr.dir)
    return merged


def _reset_for_tests() -> None:
    """Forget all arming state INCLUDING the once-per-process env check."""
    global _env_checked
    shutdown(merge=False)
    with _lock:
        _env_checked = False


def _check_env() -> Tracer | None:
    """One-time lazy arm from the environment (spawned children)."""
    global _tracer, _env_checked
    with _lock:
        if _env_checked:
            return _tracer
        _env_checked = True
    # the flight recorder inherits RA_BLACKBOX_DIR through the same
    # once-per-process gate (workers call note_role -> active_tracer on
    # entry, so their rings arm before their first telemetry event)
    from . import flightrec

    flightrec.maybe_arm_from_env()
    d = os.environ.get(ENV_VAR, "")
    if d:
        try:
            tr = Tracer(d, role=_role or "worker")
        except OSError:
            return None  # unwritable inherited dir: stay disarmed
        with _lock:
            _tracer = tr
    return _tracer


def active_tracer() -> Tracer | None:
    """The armed tracer, lazily arming from the inherited env once.

    The hot-path accessor: disarmed cost is one None-check plus one
    bool check after the first call.
    """
    tr = _tracer
    if tr is not None:
        return tr
    if _env_checked:
        return None
    return _check_env()


def note_role(role: str) -> None:
    """Label this process's trace track (call at worker entry points)."""
    global _role
    _role = role
    tr = active_tracer()
    if tr is not None:
        tr.set_role(role)
    fr = _flight
    if fr is not None:
        fr.role = role


def recording() -> bool:
    """True when ANY event sink is live (tracer or flight-recorder ring).

    The guard for call sites that measure endpoints themselves (e.g.
    ``metrics.DispatchTimer``): they must keep timing when the always-on
    black box is the only consumer.
    """
    return active_tracer() is not None or _flight is not None


def complete(
    name: str, t0_pc: float, t1_pc: float, cat: str = "", args: dict | None = None
) -> None:
    """Record a finished span from already-measured perf_counter endpoints."""
    tr = active_tracer()
    if tr is not None:
        tr.complete(name, t0_pc, t1_pc, cat, args)
    fr = _flight
    if fr is not None:
        fr.span(name, t0_pc, t1_pc, args)


def instant(name: str, args: dict | None = None) -> None:
    tr = active_tracer()
    if tr is not None:
        tr.instant(name, args)
    fr = _flight
    if fr is not None:
        fr.instant(name, args)


def timed(name: str, fn, *args, **span_args):
    """Run ``fn(*args)`` under a span; zero-wrapping when disarmed."""
    if not recording():
        return fn(*args)
    t0 = time.perf_counter()
    out = fn(*args)
    complete(name, t0, time.perf_counter(), args=span_args or None)
    return out


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_name", "_args", "_t0")

    def __init__(self, name: str, args: dict | None):
        self._name, self._args = name, args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        # module-level complete() fans out to BOTH sinks (tracer shard
        # and flight-recorder ring), whichever subset is armed at exit
        complete(self._name, self._t0, time.perf_counter(), args=self._args)
        return False


def span(name: str, **args):
    """``with obs.span("stage.name"): ...`` — a shared no-op when disarmed."""
    if active_tracer() is None and _flight is None:
        return _NULL_SPAN
    return _Span(name, args or None)


# -- metrics module surface --------------------------------------------------


def add_lines(n: int) -> None:
    """Feed the cumulative line counter (ThroughputMeter.tick calls this)."""
    m = _metrics
    if m is not None:
        m.add_lines(n)


def metric_event(kind: str, **fields) -> None:
    """Push one immediate event record (checkpoint saves, recoveries...)."""
    m = _metrics
    if m is not None:
        m.event(kind, fields)


def typed_event(kind: str, **fields) -> None:
    """One typed service event on BOTH planes at once.

    The SLO / lineage / trend emitters (DESIGN §24) publish each event
    as a trace instant (which the armed flight-recorder tap also
    captures, so ``slo.breach`` lands in a postmortem ring) AND as a
    metrics-JSONL event record — one call site, so the two planes can
    never carry different stories about the same transition.
    """
    instant(kind, args=fields)
    metric_event(kind, **fields)


def register_sampler(name: str, fn) -> None:
    """Expose a live gauge callback (``fn() -> dict``) to snapshots.

    Always registered (module-level registry), independent of the
    metrics plane's arming: the JSONL snapshotter reads whatever is live
    when it ticks, and a flight-recorder crash dump reads the same
    registry — one gauge surface, two consumers.
    """
    _register_sampler_impl(name, fn)


def unregister_sampler(name: str) -> None:
    _unregister_sampler_impl(name)


def metrics_snapshot() -> dict | None:
    """Force one snapshot record now (tests; end-of-phase markers)."""
    m = _metrics
    return m.snapshot() if m is not None else None


def metrics_active() -> bool:
    return _metrics is not None


def metrics_health() -> dict | None:
    """Snapshotter liveness + tick-error counters (None when disarmed).

    Serve's degraded-mode plane polls this: consec_errors > 0 marks the
    metrics subsystem degraded, a clean tick afterwards re-arms it.
    """
    m = _metrics
    return m.health() if m is not None else None


# -- merge -------------------------------------------------------------------


def merge_trace(trace_dir: str, out_path: str | None = None) -> str:
    """Merge every per-PID shard into one Chrome trace JSON.

    Tolerant by design: a shard's torn final line (a worker killed
    mid-write) and entirely unreadable shards are skipped — after a
    chaos run the surviving timeline must still load.  Events sort by
    timestamp so the file diffs stably and streams into viewers.
    """
    out_path = out_path or os.path.join(trace_dir, "trace.json")
    events: list[dict] = []
    for shard in sorted(glob.glob(os.path.join(trace_dir, "trace-*.jsonl"))):
        try:
            with open(shard, "r", encoding="utf-8", errors="replace") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue  # torn tail of a crashed worker's shard
                    if isinstance(ev, dict) and "ph" in ev:
                        events.append(ev)
        except OSError:
            continue
    events.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0)))
    # per-PID tmp + atomic rename: in a multi-rank job every launcher
    # merges the shared directory at its own exit, so concurrent merges
    # must each publish a COMPLETE file (last writer wins) rather than
    # interleave writes into one shared tmp path
    tmp = f"{out_path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        os.replace(tmp, out_path)
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return out_path
