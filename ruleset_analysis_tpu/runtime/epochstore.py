"""Durable epoch store with segment-tree range merges (DESIGN §25).

The window ring answers "last K windows" and nothing older survives it;
the deletion decision the paper's workflow culminates in ("was this rule
used in the last 90 days, and when did it last hit?") previously needed
a raw-traffic replay the WAL only retains up to its budget.  The merge
laws are already proven associative and commutative (add64 counts,
wrap-add32 CMS/talkers, max HLL — serve.merge_register_arrays, property
pinned since the ring landed), which is exactly the license a segment
tree needs: any grouping of the same epochs folds to the same bits.

This module turns that license into a historical query plane:

- **Level-0 chain.**  Every rotated window spills here as one CRC'd
  record (the RAEP1 epoch frame the distributed merge tier already
  speaks) in a :class:`EpochStoreLog` — the WAL's own segment discipline
  (magic + ``u32 len | u32 crc`` framing, O_APPEND durability, torn-tail
  clip, quarantine-and-continue) under a store-private magic.  Level-0
  seq ``s`` IS window ``base + s``: seq arithmetic makes every gap
  exactly attributable, no side index to trust.

- **Summary levels.**  A binary-counter compactor: whenever level ``k``
  reaches an even node count, its last aligned pair merges into ONE
  level-``k+1`` node spanning ``2^(k+1)`` windows.  Compaction only ever
  APPENDS the new node — the append is the atomic link (a torn tail is
  clipped at open, a missing parent is rebuilt from its children), so a
  SIGKILL mid-compaction leaves a readable store with zero lost epochs.
  A pair it must not merge (keyspace migration inside the span, damaged
  child) appends a typed **hole** node instead: numbering stays dense,
  queries fall through to finer levels.

- **Range queries.**  ``[t0,t1]`` decomposes greedily into at most
  ``2 * log2(n)`` aligned stored aggregates (largest power-of-two node
  that fits, falling to finer levels when a node is evicted, damaged or
  a hole) and one merge fold — bit-identical to the linear fold over the
  raw epochs, pinned by tests/test_epochstore.py.  A range the store
  cannot cover completely returns a typed ``range_incomplete`` marker
  (reason + first missing window), never a silent partial report.

- **Last-hit + trend planes.**  Spill incrementally maintains a
  per-rule last-hit map (window id + wall time of the last window with
  nonzero hits — the quiet horizon ``safe_to_delete`` evidence cites)
  and diffs adjacent epochs through report.trend_events for
  ``rule_burst``/``rule_quiet`` rows at store granularity.

Wired by ``serve --epoch-store DIR`` (runtime/serve.py: spill at rotate,
HTTP ``/report/range`` + ``/report/last-hit``), per tenant lane by
runtime/tenantserve.py, and at rank 0 post-merge by runtime/distserve.py.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import re
import threading
from collections import deque

import numpy as np

from ..errors import AnalysisError
from . import faults
from .wal import WriteAheadLog

#: store-private segment magic: a store chain must never replay as an
#: ingest WAL or an epoch spool (and vice versa)
STORE_MAGIC = b"RAESTOR1"
_LEVEL_RE = re.compile(r"^L(\d{2})$")
#: in-memory tail of store-granularity trend events served on
#: ``/report/last-hit`` (bounded: this is a view, not a ledger)
TREND_TAIL = 256


def _atomic_write_json(path: str, obj) -> None:
    import tempfile

    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def range_incomplete(lo, hi, reason: str, window=None) -> dict:
    """The typed refusal a partial range answer must become.

    ``reason`` ∈ empty_range / empty_store / beyond_frontier /
    keyspace_migration / missing (evicted, quarantined or hole);
    ``window`` pins the first window the store could not produce.
    """
    m: dict = {"range_incomplete": True, "from": lo, "to": hi,
               "reason": reason}
    if window is not None:
        m["window"] = int(window)
    return m


class EpochStoreLog(WriteAheadLog):
    """One level's append-only node chain (the WAL discipline verbatim:
    O_APPEND records, CRC quarantine, torn-tail clip, seq-gap math).
    Node seq within level ``k`` is implicit: node ``j`` spans windows
    ``[base + j*2^k, base + (j+1)*2^k)``."""

    _MAGICS = (STORE_MAGIC,)
    _WRITE_MAGIC = STORE_MAGIC
    #: one node carries a full register image (counts/CMS/HLL planes)
    _MAX_RECORD = 256 << 20

    @classmethod
    def _decode_record(cls, payload: bytes, magic: bytes) -> tuple:
        return (payload,)


# ---------------------------------------------------------------------------
# Aggregates: the unit compaction merges and queries fold.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class EpochAgg:
    """One stored node: register image + accounting over ``span``.

    ``tables`` keeps the UNBOUNDED per-(acl, src) talker estimates
    (max-deduped — the same law TopKTracker.offer applies) rather than a
    capacity-bound tracker: bounded trackers evict order-dependently, so
    only the unbounded table keeps range folds grouping-independent.
    """

    span: tuple[int, int]  # [lo, hi) window ids
    arrays: dict[str, np.ndarray]
    summary: dict
    tables: dict[int, dict[int, int]]
    quarantine: dict[tuple, int]


def _summary_from_meta(meta: dict) -> dict:
    s = {
        "windows": 1,
        "lines": int(meta.get("lines", 0)),
        "parsed": int(meta.get("parsed", 0)),
        "skipped": int(meta.get("skipped", 0)),
        "chunks": int(meta.get("chunks", 0)),
        "drops": int(meta.get("drops", 0)),
        "started_unix": float(meta.get("started_unix") or 0.0),
        "ended_unix": float(meta.get("ended_unix") or 0.0),
        "incomplete": [int(meta["id"])] if meta.get("incomplete") else [],
    }
    return s


def _merge_summaries(a: dict, b: dict) -> dict:
    return {
        "windows": a["windows"] + b["windows"],
        "lines": a["lines"] + b["lines"],
        "parsed": a["parsed"] + b["parsed"],
        "skipped": a["skipped"] + b["skipped"],
        "chunks": a["chunks"] + b["chunks"],
        "drops": a["drops"] + b["drops"],
        "started_unix": min(a["started_unix"], b["started_unix"]),
        "ended_unix": max(a["ended_unix"], b["ended_unix"]),
        "incomplete": a["incomplete"] + b["incomplete"],
    }


def _merge_tables(
    a: dict[int, dict[int, int]], b: dict[int, dict[int, int]]
) -> dict[int, dict[int, int]]:
    out = {acl: dict(t) for acl, t in a.items()}
    for acl, t in b.items():
        d = out.setdefault(acl, {})
        for src, est in t.items():
            # per-window CMS estimates of the SAME talker max-dedup,
            # exactly like TopKTracker.offer — max is associative and
            # commutative, so the fold shape cannot change the table
            d[src] = max(d.get(src, 0), est)
    return out


def merge_aggs(a: EpochAgg, b: EpochAgg) -> EpochAgg:
    """Merge two ADJACENT aggregates under the register merge laws."""
    from .serve import _merge_quarantine, merge_register_arrays

    if a.span[1] != b.span[0]:
        raise AnalysisError(
            f"epoch store cannot merge non-adjacent spans "
            f"{a.span} and {b.span}"
        )
    q = dict(a.quarantine)
    _merge_quarantine(q, b.quarantine)
    return EpochAgg(
        span=(a.span[0], b.span[1]),
        arrays=merge_register_arrays([a.arrays, b.arrays]),
        summary=_merge_summaries(a.summary, b.summary),
        tables=_merge_tables(a.tables, b.tables),
        quarantine=q,
    )


def _encode_tables(tables: dict[int, dict[int, int]]) -> dict:
    return {
        str(acl): {str(src): int(est) for src, est in t.items()}
        for acl, t in tables.items()
    }


def _decode_tables(obj: dict) -> dict[int, dict[int, int]]:
    return {
        int(acl): {int(src): int(est) for src, est in t.items()}
        for acl, t in obj.items()
    }


def _pack_node(agg: EpochAgg, *, level: int, meta: dict | None = None) -> bytes:
    """One node -> RAEP1 frame bytes (the distributed tier's CRC'd epoch
    encoding; parallel/distributed.py owns the format)."""
    from ..parallel.distributed import pack_epoch_payload

    extra = {
        "level": int(level),
        "span": [int(agg.span[0]), int(agg.span[1])],
        "summary": agg.summary,
        "tables": _encode_tables(agg.tables),
        "quarantine": [
            [*k, int(v)] for k, v in sorted(agg.quarantine.items())
        ],
    }
    if meta is not None:
        extra["meta"] = meta  # level 0 keeps the full window meta
    return pack_epoch_payload(agg.arrays, extra)


def _pack_hole(span: tuple[int, int], level: int) -> bytes:
    """A dense-numbering placeholder for a node that must not exist
    (keyspace migration inside the span, or a damaged child): queries
    treat it as unavailable and fall through to finer levels."""
    from ..parallel.distributed import pack_epoch_payload

    return pack_epoch_payload({}, {
        "level": int(level), "span": [int(span[0]), int(span[1])],
        "hole": True,
    })


def _unpack_node(payload: bytes) -> EpochAgg | None:
    """RAEP1 frame -> aggregate; ``None`` for holes.  Raises typed on
    corruption the CRC catches (caller quarantines via the chain)."""
    from ..parallel.distributed import unpack_epoch_payload

    arrays, extra = unpack_epoch_payload(payload)
    if extra.get("hole"):
        return None
    span = tuple(int(x) for x in extra["span"])
    return EpochAgg(
        span=(span[0], span[1]),
        arrays=arrays,
        summary=extra["summary"],
        tables=_decode_tables(extra.get("tables", {})),
        quarantine={
            tuple(row[:-1]): int(row[-1])
            for row in extra.get("quarantine", [])
        },
    )


def agg_from_epoch(ep) -> EpochAgg:
    """A serve WindowEpoch -> its level-0 aggregate."""
    wid = int(ep.meta["id"])
    return EpochAgg(
        span=(wid, wid + 1),
        arrays=ep.arrays,
        summary=_summary_from_meta(ep.meta),
        tables=ep.tracker_tables,
        quarantine=dict(ep.quarantine),
    )


# ---------------------------------------------------------------------------
# The store.
# ---------------------------------------------------------------------------


class EpochStore:
    """Durable window history + segment-tree aggregates for one serve
    process (single-writer; range queries may come from HTTP threads).

    Lifecycle: construct (scans chains, repairs missing summary nodes,
    loads the manifest/last-hit planes), then :meth:`bind_base` with the
    first window id this run will publish — a fresh store adopts it, a
    resumed store checks it against the spill frontier so a window-id
    gap is a typed refusal, never silent misnumbering.
    """

    MANIFEST = "manifest.json"
    INDEX = "index.jsonl"
    LASTHIT = "lasthit.json"

    def __init__(
        self,
        store_dir: str,
        *,
        budget_bytes: int = 512 << 20,
        trend_threshold: float = 0.0,
    ):
        if budget_bytes < 1 << 20:
            raise AnalysisError(
                f"epoch store budget must be >= 1 MiB, got {budget_bytes}"
            )
        self.dir = os.path.abspath(store_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.budget_bytes = int(budget_bytes)
        self.trend_threshold = float(trend_threshold)
        self._lock = threading.RLock()
        # node segments stay small relative to the budget so eviction
        # (whole oldest segment) is granular
        self._segment_bytes = max(64 << 10, min(4 << 20, budget_bytes // 16))
        self._chains: dict[int, EpochStoreLog] = {}
        #: the odd (unpaired) in-memory aggregate per level — an append
        #: cache only; a restart reloads pairs from disk
        self._carry: dict[int, EpochAgg | None] = {}
        self._labels: list[tuple] | None = None
        self.base: int | None = None
        self.eras: list[dict] = []  # {"start": wid, "generation": g}
        self.spilled_total = 0
        self.compactions_total = 0
        self.holes_total = 0
        self.range_queries_total = 0
        self.range_incomplete_total = 0
        self.evicted_epochs_total = 0
        self.evicted_nodes_total = 0
        self.trend_events_total = 0
        self.trend_tail: deque[dict] = deque(maxlen=TREND_TAIL)
        self._trend_state: dict[str, str] = {}
        self._trend_prev: dict | None = None
        self.last_hit: dict[str, dict] = {}
        self._index: list[dict] = []  # {"w","s","e","lines"} per spill
        self._index_fd: int | None = None
        self._load()
        self._repair()

    # -- open / scan ------------------------------------------------------
    def _chain(self, level: int) -> EpochStoreLog:
        c = self._chains.get(level)
        if c is None:
            c = EpochStoreLog(
                os.path.join(self.dir, f"L{level:02d}"),
                segment_bytes=self._segment_bytes,
                # store-level eviction is explicit (_evict_over_budget);
                # a chain must never silently drop its own head
                budget_bytes=1 << 62,
            )
            self._chains[level] = c
        return c

    def _load(self) -> None:
        for name in sorted(os.listdir(self.dir)):
            m = _LEVEL_RE.match(name)
            if m and os.path.isdir(os.path.join(self.dir, name)):
                self._chain(int(m.group(1)))
        mpath = os.path.join(self.dir, self.MANIFEST)
        try:
            with open(mpath) as f:
                man = json.load(f)
            self.base = int(man["base"])
            self.eras = list(man.get("eras", []))
        except (OSError, ValueError, KeyError):
            self.base = None
        try:
            with open(os.path.join(self.dir, self.LASTHIT)) as f:
                self.last_hit = json.load(f).get("rules", {})
        except (OSError, ValueError):
            self.last_hit = {}
        # the window<->wall-time index: jsonl with the lineage ledger's
        # torn-tail law (a SIGKILL tears at most the final line)
        ipath = os.path.join(self.dir, self.INDEX)
        try:
            with open(ipath, "rb") as f:
                lines = f.read().split(b"\n")
            lines.pop()  # b"" after a complete final record, else torn
            for ln in lines:
                if ln.strip():
                    self._index.append(json.loads(ln))
        except (OSError, ValueError):
            self._index = []

    def _write_manifest(self) -> None:
        _atomic_write_json(os.path.join(self.dir, self.MANIFEST), {
            "base": self.base, "eras": self.eras,
        })

    def bind_base(self, win_id: int) -> None:
        """Adopt (fresh) or check (resumed) this run's first window id."""
        with self._lock:
            if self.base is None:
                self.base = int(win_id)
                self._write_manifest()
                return
            frontier = self.base + self._chain(0).next_seq
            if win_id > frontier:
                raise AnalysisError(
                    f"epoch store at {self.dir} ends at window "
                    f"{frontier - 1} but this run starts at {win_id}: "
                    f"the gap would misnumber history — point "
                    f"--epoch-store at a fresh directory or resume the "
                    f"run the store belongs to"
                )

    def _repair(self) -> None:
        """Rebuild summary nodes a crash left unwritten.

        Invariant restored: ``level k count == level k-1 count // 2``
        for every level.  Children read back from disk; an unreadable or
        hole child makes the parent a hole (dense numbering, queries
        fall through) — repair never blocks an open.
        """
        k = 1
        while True:
            below = self._chains.get(k - 1)
            if below is None or below.next_seq < 2:
                break
            chain = self._chain(k)
            expected = below.next_seq // 2
            while chain.next_seq < expected:
                j = chain.next_seq
                left = self._load_node(k - 1, 2 * j)
                right = self._load_node(k - 1, 2 * j + 1)
                if left is None or right is None or not self._pair_ok(
                    left, right
                ):
                    lo = (self.base or 0) + (j << k)
                    chain.append_bytes(_pack_hole((lo, lo + (1 << k)), k))
                    self.holes_total += 1
                else:
                    agg = merge_aggs(left, right)
                    chain.append_bytes(_pack_node(agg, level=k))
                    self.compactions_total += 1
            k += 1

    # -- spill + compaction ----------------------------------------------
    def set_labels(self, labels: list[tuple] | None) -> None:
        """(firewall, acl, index) per key id — the last-hit/trend planes
        need rule identity; serve refreshes this at install/reload."""
        with self._lock:
            self._labels = labels

    def frontier_window(self) -> int | None:
        """Last durably spilled window id (None while empty)."""
        with self._lock:
            if self.base is None:
                return None
            n = self._chain(0).next_seq
            return self.base + n - 1 if n else None

    def spill(self, ep) -> bool:
        """Durably append one rotated window; returns False for a
        duplicate (resume replay re-publishing an already-spilled
        window), True once the epoch and its summaries are on disk.

        Fires the ``epochstore.spill`` fault site first: an injected
        (or real) failure surfaces BEFORE any bytes land, so the caller
        can degrade with the store frontier still consistent.
        """
        wid = int(ep.meta["id"])
        with self._lock:
            if self.base is None:
                self.bind_base(wid)
            chain = self._chain(0)
            frontier = self.base + chain.next_seq
            if wid < frontier:
                return False
            if wid > frontier:
                raise AnalysisError(
                    f"epoch store spill gap: expected window {frontier}, "
                    f"got {wid} (a skipped spill would misnumber history)"
                )
            faults.fire("epochstore.spill")
            agg = agg_from_epoch(ep)
            chain.append_bytes(_pack_node(agg, level=0, meta=ep.meta))
            self.spilled_total += 1
            self._append_index(ep.meta)
            self._note_last_hit(ep)
            self._trend_step(ep)
            self._promote(0, agg)
            self._evict_over_budget()
            return True

    def _pair_ok(self, left: EpochAgg, right: EpochAgg) -> bool:
        """A summary node must not straddle a keyspace migration: the
        register key spaces differ (shapes may too), so the merge would
        be meaningless at best.  Queries refuse pre-era ranges anyway;
        the hole keeps numbering dense."""
        lo, hi = left.span[0], right.span[1]
        return not any(lo < int(e["start"]) < hi for e in self.eras)

    def _promote(self, level: int, agg: EpochAgg | None) -> None:
        """Binary-counter compaction: when level ``k`` turns even, merge
        its last pair one level up (``agg`` None == the new node is a
        hole; holes propagate up as holes)."""
        chain = self._chain(level)
        if chain.next_seq % 2 == 1:
            self._carry[level] = agg
            return
        left = self._carry.get(level)
        if left is None or agg is None or left.span[1] != agg.span[0]:
            # carry lost to a restart (or it IS a hole): reload the pair
            j = chain.next_seq - 2
            left = self._load_node(level, j)
            if agg is None:
                agg = self._load_node(level, j + 1)
        self._carry[level] = None
        up = level + 1
        if left is None or agg is None or not self._pair_ok(left, agg):
            span_lo = (self.base or 0) + ((chain.next_seq - 2) << level)
            self._chain(up).append_bytes(
                _pack_hole((span_lo, span_lo + (2 << level)), up)
            )
            self.holes_total += 1
            self._promote(up, None)
            return
        # the crash window the chaos schedules pin: a kill between here
        # and the append must leave every lower level intact (repair
        # rebuilds this node from its children at next open)
        faults.fire("epochstore.compact")
        merged = merge_aggs(left, agg)
        self._chain(up).append_bytes(_pack_node(merged, level=up))
        self.compactions_total += 1
        self._promote(up, merged)

    def mark_era(self, win_id: int, generation: int) -> None:
        """A non-identity ruleset migration: windows >= ``win_id`` live
        in a new register key space.  Ranges reaching across (or before)
        the newest era boundary answer ``range_incomplete``."""
        with self._lock:
            self.eras.append({
                "start": int(win_id), "generation": int(generation),
            })
            self._write_manifest()
            # the carried aggregates are old-space images; drop them so
            # compaction reloads (and hole-fills) across the boundary
            self._carry.clear()
            self._trend_prev = None
            self._trend_state.clear()

    def _append_index(self, meta: dict) -> None:
        row = {
            "w": int(meta["id"]),
            "s": float(meta.get("started_unix") or 0.0),
            "e": float(meta.get("ended_unix") or 0.0),
            "lines": int(meta.get("lines", 0)),
        }
        if self._index_fd is None:
            self._index_fd = os.open(
                os.path.join(self.dir, self.INDEX),
                os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644,
            )
        os.write(self._index_fd, (
            json.dumps(row, sort_keys=True, separators=(",", ":")) + "\n"
        ).encode())
        self._index.append(row)

    # -- last-hit + trend planes ------------------------------------------
    def _hit_totals(self, arrays: dict) -> np.ndarray:
        u64 = np.uint64
        return arrays["counts_lo"].astype(u64) + (
            arrays["counts_hi"].astype(u64) << u64(32)
        )

    def _note_last_hit(self, ep) -> None:
        if self._labels is None:
            return
        tot = self._hit_totals(ep.arrays)
        wid = int(ep.meta["id"])
        unix = float(ep.meta.get("ended_unix") or 0.0)
        for kid in np.nonzero(tot)[0]:
            fw, acl, idx = self._labels[int(kid)]
            self.last_hit[f"{fw} {acl} {idx}"] = {
                "window": wid, "unix": round(unix, 3),
                "hits": int(tot[kid]),
            }
        _atomic_write_json(os.path.join(self.dir, self.LASTHIT), {
            "rules": self.last_hit, "frontier": wid,
        })

    def _trend_step(self, ep) -> None:
        """Adjacent-epoch rate deltas through the report plane's
        trend_events (same thresholds/hysteresis as live publication,
        store granularity)."""
        if self.trend_threshold <= 0 or self._labels is None:
            return
        from . import report as report_mod

        tot = self._hit_totals(ep.arrays)
        per_rule = []
        for kid in np.nonzero(tot)[0]:
            fw, acl, idx = self._labels[int(kid)]
            per_rule.append({
                "firewall": fw, "acl": acl, "index": idx,
                "hits": int(tot[kid]),
            })
        rep = {
            "per_rule": per_rule,
            "totals": {"lines_total": int(ep.meta.get("lines", 0))},
        }
        if self._trend_prev is not None:
            for ev in report_mod.trend_events(
                self._trend_prev, rep,
                threshold=self.trend_threshold, state=self._trend_state,
            ):
                ev = dict(ev)
                ev["window"] = int(ep.meta["id"])
                self.trend_tail.append(ev)
                self.trend_events_total += 1
        self._trend_prev = rep

    def last_hit_obj(self) -> dict:
        with self._lock:
            return {
                "frontier": self.frontier_window(),
                "rules": dict(self.last_hit),
                "trend_tail": list(self.trend_tail),
            }

    # -- queries ----------------------------------------------------------
    def _load_node(self, level: int, j: int) -> EpochAgg | None:
        chain = self._chains.get(level)
        if chain is None:
            return None
        rec = chain.read_record(j)
        if rec is None:
            return None
        try:
            return _unpack_node(rec[0])
        except AnalysisError:
            return None  # CRC passed but framing did not: treat as gap

    def resolve_range(self, frm: str | None, to: str | None):
        """HTTP query params -> inclusive window-id bounds.

        Values >= 10^8 read as unix seconds and map through the spill
        index (first window ending at/after ``from``, last starting
        at/before ``to``); smaller values are window ids.  ``None``
        bounds default to the store's full extent.
        """
        def parse(v, *, is_from):
            if v is None or v == "":
                return None
            try:
                x = float(v)
            except ValueError as e:
                raise AnalysisError(f"bad range bound {v!r}") from e
            if x < 1e8:
                return int(x)
            with self._lock:
                if is_from:
                    for row in self._index:
                        if row["e"] >= x:
                            return row["w"]
                    return (self.frontier_window() or 0) + 1  # future
                prev = None
                for row in self._index:
                    if row["s"] <= x:
                        prev = row["w"]
                    else:
                        break
                return prev if prev is not None else -1  # before history

        return parse(frm, is_from=True), parse(to, is_from=False)

    def _pick_level(self, s: int, e: int) -> int:
        """Largest level whose aligned node starting at seq ``s`` fits
        inside ``[s, e)`` — the greedy step that caps the decomposition
        at ``2*log2(n)`` nodes."""
        k = 0
        top = max(self._chains, default=0)
        while k < top:
            size = 2 << k
            if s % size or s + size > e:
                break
            k += 1
        return k

    def range_agg(self, lo: int | None, hi: int | None):
        """Inclusive ``[lo, hi]`` -> ``(EpochAgg, None)`` or
        ``(None, range_incomplete marker)``.  Never partial."""
        with self._lock:
            self.range_queries_total += 1
            out = self._range_agg_locked(lo, hi)
            if out[0] is None:
                self.range_incomplete_total += 1
            return out

    def _range_agg_locked(self, lo, hi):
        if self.base is None or self._chain(0).next_seq == 0:
            return None, range_incomplete(lo, hi, "empty_store")
        frontier = self.base + self._chain(0).next_seq  # first unspilled
        if lo is None:
            lo = self.base
        if hi is None:
            hi = frontier - 1
        lo, hi = int(lo), int(hi)
        if lo > hi:
            return None, range_incomplete(lo, hi, "empty_range")
        if hi >= frontier:
            return None, range_incomplete(
                lo, hi, "beyond_frontier", frontier
            )
        if lo < self.base:
            return None, range_incomplete(lo, hi, "missing", lo)
        era_lo = max(
            (int(e["start"]) for e in self.eras), default=self.base
        )
        if lo < era_lo:
            # pre-migration registers live in a dead key space: refuse
            # typed rather than merge incomparable counters
            return None, range_incomplete(
                lo, hi, "keyspace_migration", era_lo - 1
            )
        s, e = lo - self.base, hi - self.base + 1
        agg: EpochAgg | None = None
        w = s
        while w < e:
            k = self._pick_level(w, e)
            node = None
            while k >= 0:
                node = self._load_node(k, w >> k)
                if node is not None:
                    break
                k -= 1
            if node is None:
                return None, range_incomplete(
                    lo, hi, "missing", self.base + w
                )
            agg = node if agg is None else merge_aggs(agg, node)
            w += 1 << max(k, 0)
        return agg, None

    def naive_range_agg(self, lo: int, hi: int):
        """The linear per-epoch left fold the segment tree must match
        bit-for-bit (and beat by >=10x at depth): same guards, level-0
        nodes only.  The bench's baseline leg and the property test's
        oracle."""
        with self._lock:
            if self.base is None or self._chain(0).next_seq == 0:
                return None, range_incomplete(lo, hi, "empty_store")
            frontier = self.base + self._chain(0).next_seq
            if lo > hi:
                return None, range_incomplete(lo, hi, "empty_range")
            if hi >= frontier:
                return None, range_incomplete(
                    lo, hi, "beyond_frontier", frontier
                )
            agg: EpochAgg | None = None
            for w in range(lo - self.base, hi - self.base + 1):
                node = self._load_node(0, w)
                if node is None:
                    return None, range_incomplete(
                        lo, hi, "missing", self.base + w
                    )
                agg = node if agg is None else merge_aggs(agg, node)
            return agg, None

    # -- budget + accounting ----------------------------------------------
    def _evict_over_budget(self) -> None:
        """Whole-oldest-segment eviction from the FINEST level holding
        more than one segment: raw epochs go first (their coarse
        summaries still answer aligned queries over the evicted span),
        summaries only when no finer level has anything left to give."""
        while True:
            total = sum(
                c.stats()["bytes"] for c in self._chains.values()
            )
            if total <= self.budget_bytes:
                return
            victim = None
            for k in sorted(self._chains):
                c = self._chains[k]
                if len(c._segments) > 1:
                    victim = (k, c)
                    break
            if victim is None:
                return  # one segment per level: nothing evictable
            k, c = victim
            freed = c.gc(c._segments[0].end)
            if k == 0:
                self.evicted_epochs_total += freed
            else:
                self.evicted_nodes_total += freed
            from . import obs

            obs.instant("epochstore.evict", args={
                "level": k, "nodes": freed,
            })

    def stats(self) -> dict:
        with self._lock:
            per_level = {
                k: c.stats() for k, c in sorted(self._chains.items())
            }
            n0 = self._chain(0).next_seq
            return {
                "dir": self.dir,
                "base": self.base,
                "last_spilled_window": self.frontier_window(),
                "levels": len(self._chains),
                "epochs": int(sum(
                    s.count for s in self._chain(0)._segments
                )),
                "nodes": int(sum(
                    sum(s.count for s in c._segments)
                    for c in self._chains.values()
                )),
                "bytes": int(sum(
                    v["bytes"] for v in per_level.values()
                )),
                "spilled_total": self.spilled_total,
                "compactions_total": self.compactions_total,
                "holes_total": self.holes_total,
                "evicted_epochs_total": self.evicted_epochs_total,
                "evicted_nodes_total": self.evicted_nodes_total,
                "quarantined_segments": int(sum(
                    len(c.quarantined) for c in self._chains.values()
                )),
                "range_queries_total": self.range_queries_total,
                "range_incomplete_total": self.range_incomplete_total,
                "trend_events_total": self.trend_events_total,
                "last_hit_rules": len(self.last_hit),
                "depth": int(math.log2(n0)) + 1 if n0 else 0,
                "eras": len(self.eras),
            }

    def gauges(self) -> dict:
        """Flat numerics for /metrics (JSON and prom render from this
        one dict — parity pinned by verify/registry.py::audit_epochstore)."""
        s = self.stats()
        fw = s["last_spilled_window"]
        return {
            "epochstore_spilled_total": s["spilled_total"],
            "epochstore_epochs": s["epochs"],
            "epochstore_levels": s["levels"],
            "epochstore_nodes": s["nodes"],
            "epochstore_bytes": s["bytes"],
            "epochstore_depth": s["depth"],
            "epochstore_compactions_total": s["compactions_total"],
            "epochstore_holes_total": s["holes_total"],
            "epochstore_evicted_epochs_total": s["evicted_epochs_total"],
            "epochstore_evicted_nodes_total": s["evicted_nodes_total"],
            "epochstore_quarantined_segments": s["quarantined_segments"],
            "epochstore_last_window": fw if fw is not None else -1,
            "epochstore_range_queries_total": s["range_queries_total"],
            "epochstore_range_incomplete_total":
                s["range_incomplete_total"],
            "epochstore_trend_events_total": s["trend_events_total"],
            "epochstore_last_hit_rules": s["last_hit_rules"],
        }

    def frontier(self) -> dict:
        """The postmortem join (/lineage + doctor): did history survive?"""
        s = self.stats()
        return {
            "last_spilled_window": s["last_spilled_window"],
            "levels": s["levels"],
            "epochs": s["epochs"],
            "holes": s["holes_total"],
            "quarantined_segments": s["quarantined_segments"],
        }

    # -- lifecycle --------------------------------------------------------
    def reset(self) -> None:
        """Fresh-run open: drop every chain and plane (mirrors the WAL
        law — a non-resume run must not graft onto stale history)."""
        with self._lock:
            for c in self._chains.values():
                c.reset()
                c.close()
            self._chains.clear()
            self._carry.clear()
            if self._index_fd is not None:
                os.close(self._index_fd)
                self._index_fd = None
            for name in (self.MANIFEST, self.INDEX, self.LASTHIT):
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    pass
            self.base = None
            self.eras = []
            self._index = []
            self.last_hit = {}
            self._trend_prev = None
            self._trend_state.clear()
            self.trend_tail.clear()

    def sync(self) -> None:
        with self._lock:
            for c in self._chains.values():
                c.sync()

    def close(self) -> None:
        with self._lock:
            for c in self._chains.values():
                c.close()
            if self._index_fd is not None:
                os.close(self._index_fd)
                self._index_fd = None


# ---------------------------------------------------------------------------
# Report-plane joins.
# ---------------------------------------------------------------------------


def attach_last_hit(rep_obj: dict, store: EpochStore) -> None:
    """Join the store's last-hit horizon into ``totals.static``: every
    ``safe_to_delete`` verdict gains the evidence the paper's workflow
    actually needs — WHEN the rule last hit, or that it never has inside
    retained history."""
    static = rep_obj.get("totals", {}).get("static")
    if not isinstance(static, dict):
        return
    horizon = store.frontier_window()
    if horizon is None:
        return
    rules: dict[str, dict] = {}
    classes = static.get("unused_classes", {})
    for rule in classes.get("safe_to_delete", []):
        hit = store.last_hit.get(rule)
        if hit is None:
            rules[rule] = {"never_hit": True}
        else:
            rules[rule] = {
                "last_hit_window": hit["window"],
                "last_hit_unix": hit["unix"],
                "quiet_windows": max(horizon - hit["window"], 0),
            }
    static["last_hit"] = {"horizon_window": horizon, "rules": rules}
